"""Pallas fused kernels vs reference math (interpret mode on CPU,
SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.pallas import (layer_norm, softmax_cross_entropy,
                                   flash_attention, fused_adam_update)


def test_layer_norm_forward_matches():
    x = np.random.randn(32, 128).astype("f4")
    w = np.random.rand(128).astype("f4") + 0.5
    b = np.random.randn(128).astype("f4")
    out = layer_norm(pt.to_tensor(x), pt.to_tensor(w), pt.to_tensor(b))
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)


def test_layer_norm_grad_matches_xla():
    x = np.random.randn(16, 64).astype("f4")
    w = np.random.rand(64).astype("f4") + 0.5
    b = np.random.randn(64).astype("f4")

    tx = pt.to_tensor(x, stop_gradient=False)
    tw = pt.Parameter(w)
    tb = pt.Parameter(b)
    (layer_norm(tx, tw, tb) * pt.to_tensor(np.arange(64, dtype="f4"))
     ).sum().backward()

    tx2 = pt.to_tensor(x, stop_gradient=False)
    tw2 = pt.Parameter(w)
    tb2 = pt.Parameter(b)
    from paddle_tpu.nn import functional as F
    (F.layer_norm(tx2, 64, tw2, tb2) *
     pt.to_tensor(np.arange(64, dtype="f4"))).sum().backward()

    np.testing.assert_allclose(np.asarray(tx.grad), np.asarray(tx2.grad),
                               atol=2e-3)
    np.testing.assert_allclose(np.asarray(tw.grad), np.asarray(tw2.grad),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(tb.grad), np.asarray(tb2.grad),
                               rtol=2e-3, atol=2e-3)


def test_softmax_xent_matches_and_grads():
    logits = np.random.randn(24, 50).astype("f4")
    labels = np.random.randint(0, 50, (24,))

    t = pt.to_tensor(logits, stop_gradient=False)
    loss = softmax_cross_entropy(t, pt.to_tensor(labels))
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + \
        logits.max(-1)
    ref = lse - logits[np.arange(24), labels]
    np.testing.assert_allclose(loss.numpy().ravel(), ref, atol=1e-4)

    loss.mean().backward()
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(50, dtype="f4")[labels]
    ref_grad = (p - onehot) / 24
    np.testing.assert_allclose(np.asarray(t.grad), ref_grad, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_sdpa(causal):
    b, h, s, d = 2, 2, 64, 16
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                          causal=causal, block_q=32, block_k=32, force=True)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, atol=2e-3)


def test_flash_attention_backward():
    b, h, s, d = 1, 2, 32, 8
    rng = np.random.RandomState(1)
    q = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    k = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    v = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    flash_attention(q, k, v, causal=True, block_q=16,
                    block_k=16, force=True).sum().backward()
    from paddle_tpu.nn import functional as F
    q2 = pt.to_tensor(q.numpy(), stop_gradient=False)
    k2 = pt.to_tensor(k.numpy(), stop_gradient=False)
    v2 = pt.to_tensor(v.numpy(), stop_gradient=False)
    F.scaled_dot_product_attention(q2, k2, v2,
                                   is_causal=True).sum().backward()
    np.testing.assert_allclose(np.asarray(q.grad), np.asarray(q2.grad),
                               atol=3e-3)
    np.testing.assert_allclose(np.asarray(k.grad), np.asarray(k2.grad),
                               atol=3e-3)
    np.testing.assert_allclose(np.asarray(v.grad), np.asarray(v2.grad),
                               atol=3e-3)


def test_fused_adam_matches_rule():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    p = rng.randn(37, 5).astype("f4")  # deliberately unaligned size
    g = rng.randn(37, 5).astype("f4")
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    b1p, b2p = b1, b2
    new_p, new_m, new_v = fused_adam_update(
        jnp.asarray(p), jnp.asarray(g), jnp.asarray(m), jnp.asarray(v),
        lr, b1p, b2p, beta1=b1, beta2=b2, eps=eps)
    m_ref = (1 - b1) * g
    v_ref = (1 - b2) * g * g
    p_ref = p - lr * (m_ref / (1 - b1p)) / (
        np.sqrt(v_ref / (1 - b2p)) + eps)
    np.testing.assert_allclose(np.asarray(new_p), p_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_m), m_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), v_ref, atol=1e-6)


def test_fused_adam_in_optimizer():
    from paddle_tpu import optimizer as opt
    w1 = pt.Parameter(np.ones((8, 4), "f4"))
    w2 = pt.Parameter(np.ones((8, 4), "f4"))
    o1 = opt.Adam(learning_rate=0.1, parameters=[w1], use_fused=True)
    o2 = opt.Adam(learning_rate=0.1, parameters=[w2])
    for o, w in ((o1, w1), (o2, w2)):
        (w * w).sum().backward()
        o.step()
        o.clear_grad()
    np.testing.assert_allclose(w1.numpy(), w2.numpy(), atol=1e-5)


def test_pallas_layer_norm_layer_flag():
    from paddle_tpu import nn
    ln = nn.LayerNorm(32, use_pallas=True)
    x = pt.to_tensor(np.random.randn(4, 32).astype("f4"))
    out = ln(x)
    o = out.numpy()
    np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-4)


def test_flash_attention_unaligned_seq():
    """Regression: tail K/V block must not be dropped (seq % block_k != 0)."""
    b, h, s, d = 1, 2, 40, 16
    rng = np.random.RandomState(3)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                          block_q=32, block_k=32, force=True)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, atol=2e-3)


def test_flash_attention_key_mask_fused():
    """Additive key-padding mask ([B,1,1,Sk], the BERT shape) is fused into
    the kernel and matches sdpa exactly (VERDICT r2 #1)."""
    b, h, s, d = 2, 2, 32, 8
    rng = np.random.RandomState(5)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    m = np.where(rng.rand(b, 1, 1, s) < 0.3, -1e9, 0.0).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                          attn_mask=pt.to_tensor(m), block_q=16,
                          block_k=16, force=True)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d) + m
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, atol=2e-3)


def test_flash_attention_key_mask_grads():
    """Backward through the fused key-mask path (the default BERT path)
    matches sdpa — guards the (1,BK) broadcast branches in both backward
    kernels."""
    b, h, s, d = 2, 2, 24, 8
    rng = np.random.RandomState(9)
    qn = rng.randn(b, h, s, d).astype("f4")
    kn = rng.randn(b, h, s, d).astype("f4")
    vn = rng.randn(b, h, s, d).astype("f4")
    mn = np.where(rng.rand(b, 1, 1, s) < 0.3, -1e9, 0.0).astype("f4")
    q = pt.to_tensor(qn, stop_gradient=False)
    k = pt.to_tensor(kn, stop_gradient=False)
    v = pt.to_tensor(vn, stop_gradient=False)
    flash_attention(q, k, v, attn_mask=pt.to_tensor(mn), block_q=16,
                    block_k=16, force=True).sum().backward()
    from paddle_tpu.nn import functional as F
    q2 = pt.to_tensor(qn, stop_gradient=False)
    k2 = pt.to_tensor(kn, stop_gradient=False)
    v2 = pt.to_tensor(vn, stop_gradient=False)
    F.scaled_dot_product_attention(
        q2, k2, v2, attn_mask=pt.to_tensor(mn)).sum().backward()
    for a, bb in ((q, q2), (k, k2), (v, v2)):
        np.testing.assert_allclose(np.asarray(a.grad), np.asarray(bb.grad),
                                   atol=3e-3)


def test_flash_attention_fully_masked_row_grads():
    """Regression (review r3): rows whose every visible key carries a
    finite -1e9 bias must still produce sdpa-matching gradients — the
    backward reconstructs p from (m, l), not the folded lse, so 1e9-scale
    scores round identically to the forward."""
    b, h, s, d = 1, 1, 24, 8
    rng = np.random.RandomState(10)
    qn = rng.randn(b, h, s, d).astype("f4")
    kn = rng.randn(b, h, s, d).astype("f4")
    vn = rng.randn(b, h, s, d).astype("f4")
    mn = np.zeros((1, 1, s, s), "f4")
    mn[0, 0, 3, :] = -1e9   # row 3 fully masked (finite bias, not -inf)
    mn[0, 0, 7, :20] = -1e9  # row 7 nearly fully masked
    q = pt.to_tensor(qn, stop_gradient=False)
    k = pt.to_tensor(kn, stop_gradient=False)
    v = pt.to_tensor(vn, stop_gradient=False)
    flash_attention(q, k, v, attn_mask=pt.to_tensor(mn),
                    block_q=8, block_k=8, force=True).sum().backward()
    from paddle_tpu.nn import functional as F
    q2 = pt.to_tensor(qn, stop_gradient=False)
    k2 = pt.to_tensor(kn, stop_gradient=False)
    v2 = pt.to_tensor(vn, stop_gradient=False)
    F.scaled_dot_product_attention(
        q2, k2, v2, attn_mask=pt.to_tensor(mn)).sum().backward()
    for a, bb in ((q, q2), (k, k2), (v, v2)):
        np.testing.assert_allclose(np.asarray(a.grad), np.asarray(bb.grad),
                                   atol=3e-3)


def test_flash_attention_full_mask_grads():
    """Full [1,1,Sq,Sk] additive mask: forward + grads match sdpa."""
    b, h, s, d = 1, 2, 24, 8
    rng = np.random.RandomState(6)
    qn = rng.randn(b, h, s, d).astype("f4")
    kn = rng.randn(b, h, s, d).astype("f4")
    vn = rng.randn(b, h, s, d).astype("f4")
    mn = (rng.randn(1, 1, s, s) * 2).astype("f4")
    q = pt.to_tensor(qn, stop_gradient=False)
    k = pt.to_tensor(kn, stop_gradient=False)
    v = pt.to_tensor(vn, stop_gradient=False)
    flash_attention(q, k, v, attn_mask=pt.to_tensor(mn), block_q=16,
                    block_k=16, force=True).sum().backward()
    from paddle_tpu.nn import functional as F
    q2 = pt.to_tensor(qn, stop_gradient=False)
    k2 = pt.to_tensor(kn, stop_gradient=False)
    v2 = pt.to_tensor(vn, stop_gradient=False)
    F.scaled_dot_product_attention(
        q2, k2, v2, attn_mask=pt.to_tensor(mn)).sum().backward()
    for a, bb in ((q, q2), (k, k2), (v, v2)):
        np.testing.assert_allclose(np.asarray(a.grad), np.asarray(bb.grad),
                                   atol=3e-3)


def test_flash_attention_dropout_fused():
    """Attention dropout is fused in-kernel: deterministic per seed,
    seed-sensitive, output stays correctly scaled (VERDICT r2 #1 — the
    old sdpa fallback under dropout is gone)."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import _flash
    b, h, s, d = 1, 2, 16, 8
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    s1 = jnp.asarray([1, 2], jnp.int32)
    s2 = jnp.asarray([3, 4], jnp.int32)
    o1 = _flash(q, k, v, None, None, s1, False, None, 16, 16, 0.4)
    o1b = _flash(q, k, v, None, None, s1, False, None, 16, 16, 0.4)
    o2 = _flash(q, k, v, None, None, s2, False, None, 16, 16, 0.4)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o1b))
    assert np.abs(np.asarray(o1) - np.asarray(o2)).max() > 1e-4


def test_flash_attention_dropout_grad_finite_difference():
    """The fused backward regenerates the identical dropout mask: custom
    VJP matches finite differences (mask is fixed given the seed)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import _flash
    b, h, s, d = 1, 1, 16, 8
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    w = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    seed = jnp.asarray([5, 6], jnp.int32)

    def f(q, k, v):
        return jnp.sum(_flash(q, k, v, None, None, seed, False, None, 16,
                              16, 0.3) * w)

    gq, gk, gv = jax.grad(f, (0, 1, 2))(q, k, v)
    eps, i = 1e-3, (0, 0, 3, 5)
    for arr, g, which in ((q, gq, "q"), (k, gk, "k"), (v, gv, "v")):
        args = {"q": [arr if which == "q" else q, k, v],
                "k": [q, arr if which == "k" else k, v],
                "v": [q, k, arr if which == "v" else v]}[which]
        idx = {"q": 0, "k": 1, "v": 2}[which]
        plus = list(args)
        plus[idx] = args[idx].at[i].add(eps)
        minus = list(args)
        minus[idx] = args[idx].at[i].add(-eps)
        fd = (f(*plus) - f(*minus)) / (2 * eps)
        np.testing.assert_allclose(float(fd), float(g[i]), rtol=5e-2,
                                   atol=5e-3)


def test_flash_wrapper_dropout_no_fallback_shape():
    b, h, s, d = 1, 1, 16, 8
    q = pt.to_tensor(np.random.randn(b, h, s, d).astype("f4"))
    out = flash_attention(q, q, q, dropout_p=0.5, training=True,
                          block_q=16, block_k=16, force=True)
    assert out.shape == [b, h, s, d]


def test_fused_adam_multiblock_grid():
    """Tensors bigger than one (1024, 128) block must grid-stride
    correctly (the single-block VMEM-OOM regression at BERT-embedding
    scale: 7 refs x 4096 rows blew the 16MB scoped-VMEM limit)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(1)
    n = 1024 * 128 * 2 + 77  # 2 full row-blocks + ragged tail
    p = rng.randn(n).astype("f4")
    g = rng.randn(n).astype("f4")
    m = rng.rand(n).astype("f4") * 0.1
    v = rng.rand(n).astype("f4") * 0.01
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
    new_p, new_m, new_v = fused_adam_update(
        jnp.asarray(p), jnp.asarray(g), jnp.asarray(m), jnp.asarray(v),
        lr, b1, b2, beta1=b1, beta2=b2, eps=eps)
    m_ref = b1 * m + (1 - b1) * g
    v_ref = b2 * v + (1 - b2) * g * g
    p_ref = p - lr * (m_ref / (1 - b1)) / (np.sqrt(v_ref / (1 - b2)) + eps)
    np.testing.assert_allclose(np.asarray(new_p), p_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_m), m_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), v_ref, atol=1e-6)


def test_layer_norm_multiblock_rows():
    """Row count spanning several blocks incl. a partial final block; the
    bwd dw/db accumulation must not double-count or include padding."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.layer_norm import _layer_norm2
    rng = np.random.RandomState(2)
    d = 768
    n = 683 * 2 + 11  # > 2 blocks at the 512K-element target for d=768
    x = rng.randn(n, d).astype("f4")
    w = rng.randn(d).astype("f4")
    b = rng.randn(d).astype("f4")

    def ref(x, w, b):
        mu = x.mean(1, keepdims=True)
        var = x.var(1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    out = _layer_norm2(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), 1e-5)
    np.testing.assert_allclose(np.asarray(out), ref(x, w, b), atol=2e-4)

    def f(x, w, b):
        # all-ones cotangent: the analytic dw/db checks below assume it
        return _layer_norm2(x, w, b, 1e-5).sum()

    gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    # dw/db vs analytic: db = sum(g) = n per feature? g == 1 everywhere
    xn = (x - x.mean(1, keepdims=True)) / np.sqrt(
        x.var(1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.full(d, float(n)),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), xn.sum(0), atol=2e-2)


def test_pallas_configure_overrides():
    """pallas.configure() flips the auto defaults consulted at forward/
    step time (the bench probe uses this to degrade one kernel at a
    time)."""
    from paddle_tpu.ops import pallas as P
    try:
        assert P.enabled("layer_norm") == P.on_tpu()
        P.configure(layer_norm=True, fused_adam=False)
        assert P.enabled("layer_norm") is True
        assert P.enabled("fused_adam") is False
        # a LayerNorm built BEFORE the configure() call still honors it
        from paddle_tpu import nn
        ln = nn.LayerNorm(16)
        x = pt.to_tensor(np.random.RandomState(0).randn(4, 16).astype("f4"))
        out_forced = ln(x).numpy()  # interpret-mode pallas on CPU
        P.configure(layer_norm=False)
        out_xla = ln(x).numpy()
        np.testing.assert_allclose(out_forced, out_xla, atol=1e-5)
    finally:
        P.configure(layer_norm=None, fused_adam=None)
        # None restores the measured auto defaults: layer_norm is
        # auto-on on TPU, fused_adam auto-off everywhere (it loses to
        # XLA's own update fusion — docs/perf_r04.md)
        assert P.enabled("layer_norm") == P.on_tpu()
        assert P.enabled("fused_adam") is False


def test_softmax_xent_gated_in_loss_op():
    """softmax_with_cross_entropy routes through the fused kernel when
    configure(softmax_xent=True); numerics (incl. ignore_index masking
    and grads) must match the XLA logsumexp path."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas as P
    from paddle_tpu.ops.loss import softmax_with_cross_entropy
    rng = np.random.RandomState(3)
    logits = rng.randn(6, 128, 33).astype("f4")
    label = rng.randint(0, 33, (6, 128)).astype("i4")
    label[0, :7] = -1  # ignored positions

    def run():
        x = pt.to_tensor(logits.copy())
        x.stop_gradient = False
        loss = softmax_with_cross_entropy(x, pt.to_tensor(label),
                                          ignore_index=-1)
        loss.sum().backward()
        return loss.numpy(), np.asarray(x.grad)

    try:
        P.configure(softmax_xent=True)
        l_k, g_k = run()
    finally:
        P.configure(softmax_xent=None)
    P.configure(softmax_xent=False)
    try:
        l_x, g_x = run()
    finally:
        P.configure(softmax_xent=None)
    np.testing.assert_allclose(l_k, l_x, atol=1e-4)
    np.testing.assert_allclose(g_k, g_x, atol=1e-4)


def test_pallas_configure_rejects_unknown():
    from paddle_tpu.ops import pallas as P
    import pytest
    with pytest.raises(ValueError):
        P.configure(flash_atention=False)  # typo must not pass silently


def test_softmax_xent_gated_in_cross_entropy():
    """cross_entropy (the flagship BERT loss path) routes through the
    fused kernel too; mean-reduction over non-ignored rows, weights, and
    grads must match the XLA path."""
    import jax
    from paddle_tpu.ops import pallas as P
    from paddle_tpu.ops.loss import cross_entropy
    rng = np.random.RandomState(4)
    logits = rng.randn(5, 64, 17).astype("f4")
    label = rng.randint(0, 17, (5, 64)).astype("i4")
    label[1, :9] = -1

    def run(weight=None):
        x = pt.to_tensor(logits.copy())
        x.stop_gradient = False
        loss = cross_entropy(x, pt.to_tensor(label), ignore_index=-1,
                             weight=weight)
        loss.backward()
        return float(loss.numpy()), np.asarray(x.grad)

    w = pt.to_tensor(rng.rand(17).astype("f4") + 0.5)
    try:
        P.configure(softmax_xent=True)
        l_k, g_k = run()
        lw_k, gw_k = run(weight=w)
    finally:
        P.configure(softmax_xent=None)
    P.configure(softmax_xent=False)
    try:
        l_x, g_x = run()
        lw_x, gw_x = run(weight=w)
    finally:
        P.configure(softmax_xent=None)
    np.testing.assert_allclose(l_k, l_x, rtol=1e-5)
    np.testing.assert_allclose(g_k, g_x, atol=1e-5)
    np.testing.assert_allclose(lw_k, lw_x, rtol=1e-5)
    np.testing.assert_allclose(gw_k, gw_x, atol=1e-5)


def test_softmax_xent_label_smoothing():
    """Smoothed kernel path == label_smooth + soft-label XLA path (loss
    and grads), incl. through Transformer.loss gating."""
    import jax
    from paddle_tpu.ops import pallas as P
    from paddle_tpu.ops import loss as L, one_hot
    from paddle_tpu.nn import functional as F
    rng = np.random.RandomState(7)
    eps = 0.1
    logits = rng.randn(4, 20, 29).astype("f4")
    labels = rng.randint(0, 29, (4, 20)).astype("i4")

    x1 = pt.to_tensor(logits.copy())
    x1.stop_gradient = False
    loss1 = P.softmax_cross_entropy(x1, pt.to_tensor(labels),
                                    smooth_eps=eps)
    loss1.sum().backward()

    x2 = pt.to_tensor(logits.copy())
    x2.stop_gradient = False
    soft = F.label_smooth(one_hot(pt.to_tensor(labels), 29), epsilon=eps)
    loss2 = L.softmax_with_cross_entropy(x2, soft, soft_label=True)
    loss2.sum().backward()

    np.testing.assert_allclose(loss1.numpy(), loss2.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(x1.grad), np.asarray(x2.grad),
                               atol=1e-5)


def test_transformer_loss_pallas_gate():
    """Force the softmax_xent gate on: Transformer.loss through the fused
    smoothed kernel must match its own XLA fallback path."""
    from paddle_tpu.ops import pallas as P
    from paddle_tpu.models.transformer import Transformer

    pt.seed(0)
    model = Transformer(src_vocab_size=37, tgt_vocab_size=37, d_model=16,
                        num_heads=2, d_ff=32, num_encoder_layers=1,
                        num_decoder_layers=1)
    rng = np.random.RandomState(8)
    logits = pt.to_tensor(rng.randn(2, 9, 37).astype("f4"))
    labels = pt.to_tensor(rng.randint(0, 37, (2, 9)).astype("i4"))
    try:
        P.configure(softmax_xent=True)
        l_k = float(model.loss(logits, labels).numpy())
    finally:
        P.configure(softmax_xent=None)
    P.configure(softmax_xent=False)
    try:
        l_x = float(model.loss(logits, labels).numpy())
    finally:
        P.configure(softmax_xent=None)
    np.testing.assert_allclose(l_k, l_x, rtol=1e-5)


def test_flash_min_seq_gate():
    """configure(flash_min_seq=N) routes short sequences to sdpa even
    with the kernel force-enabled (the ablation-tuned crossover knob)."""
    from paddle_tpu.ops import pallas as P
    import numpy as np
    import paddle_tpu as pt

    q = pt.to_tensor(np.random.RandomState(0).randn(1, 2, 16, 8)
                     .astype("f4"))
    try:
        P.configure(flash_attention=True, flash_min_seq=64)
        assert not P.enabled("flash_attention", seq_len=16)
        assert P.enabled("flash_attention", seq_len=128)
        # short seq runs through the sdpa fallback (no interpret-mode
        # kernel = fast) and matches plain attention
        out = P.flash_attention(q, q, q)
        from paddle_tpu.ops.nn_ops import scaled_dot_product_attention
        ref = scaled_dot_product_attention(q, q, q, training=False)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-6)
    finally:
        P.configure(flash_attention=None, flash_min_seq=None)


def test_fused_batch_norm_parity_and_grads():
    """Pallas fused BN (interpret mode) vs the XLA batch_norm path:
    forward, batch stats, running-stat update, and grads w.r.t.
    x/weight/bias must match. M=200 deliberately not a multiple of the
    row block so the masked tail is exercised."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.batch_norm import _batch_norm2

    rng = np.random.RandomState(0)
    m, c = 200, 24
    x = jnp.asarray(rng.randn(m, c).astype("f4") * 2 + 3)
    w = jnp.asarray(rng.rand(c).astype("f4") + 0.5)
    b = jnp.asarray(rng.randn(c).astype("f4"))
    g = jnp.asarray(rng.randn(m, c).astype("f4"))

    def ref(x, w, b, eps=1e-5):
        mean = jnp.mean(x, axis=0)
        var = jnp.var(x, axis=0)
        inv = jax.lax.rsqrt(var + eps)
        return (x - mean) * inv * w + b, mean, var

    out, mean, var = _batch_norm2(x, w, b, 1e-5)
    r_out, r_mean, r_var = ref(x, w, b)
    np.testing.assert_allclose(out, r_out, atol=2e-4)
    np.testing.assert_allclose(np.asarray(mean).ravel(), r_mean, atol=1e-4)
    np.testing.assert_allclose(np.asarray(var).ravel(), r_var, atol=2e-3)

    # grads through out only (the usual training path)
    g1 = jax.grad(lambda *a: jnp.sum(_batch_norm2(*a, 1e-5)[0] * g),
                  argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a)[0] * g),
                  argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(g1, g2):
        np.testing.assert_allclose(a, r, atol=3e-4)

    # grads through the DIRECT mean/var outputs stay exact too
    gm = jnp.asarray(rng.randn(c).astype("f4"))
    gv = jnp.asarray(rng.randn(c).astype("f4"))

    def take_stats(f):
        def inner(x):
            _, mean, var = f(x, w, b) if f is not _batch_norm2 else \
                f(x, w, b, 1e-5)
            return jnp.sum(mean * gm) + jnp.sum(var * gv)
        return inner

    ga = jax.grad(take_stats(_batch_norm2))(x)
    gr = jax.grad(take_stats(ref))(x)
    np.testing.assert_allclose(ga, gr, atol=3e-4)

    # large-mean regime: the sample-shifted accumulators must keep the
    # variance (raw E[x^2]-E[x]^2 loses it entirely at mean ~1e3)
    xl = jnp.asarray(rng.randn(m, c).astype("f4") + 1000.0)
    out_l, _, var_l = _batch_norm2(xl, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(var_l).ravel(),
                               jnp.var(xl, axis=0), rtol=0.05)
    assert abs(float(jnp.mean((out_l - b) / w))) < 0.1
    assert 0.8 < float(jnp.std((out_l - b) / w)) < 1.2


def test_fused_batch_norm_gated_in_layer():
    """configure(batch_norm=True) routes a channels-last BatchNorm1D
    through the Pallas kernel; training numerics (incl. running-stat
    carry) must match the XLA path, and NCHW inputs must keep the XLA
    path (no silent transpose)."""
    from paddle_tpu.ops import pallas as P
    from paddle_tpu import nn

    rng = np.random.RandomState(1)
    x = rng.randn(32, 12).astype("f4")

    def run(use):
        import paddle_tpu as pt
        pt.seed(0)
        P.configure(batch_norm=use)
        try:
            bn = nn.BatchNorm1D(12, data_format="NLC")
            bn.train()
            out = bn(pt.to_tensor(x))
            loss = (out ** 2).mean()
            loss.backward()
            return (out.numpy(), bn._mean.numpy(), bn._variance.numpy(),
                    np.asarray(bn.weight.grad))
        finally:
            P.configure(batch_norm=None)

    o1 = run(True)
    o2 = run(False)
    for a, b_ in zip(o1, o2):
        np.testing.assert_allclose(a, b_, atol=3e-4)


def test_fused_adam_multi_matches_per_tensor():
    """Multi-tensor kernel == the plain-XLA per-tensor math (shared
    beta pows, mixed shapes incl. scalar-ish and non-128-aligned)."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.fused_adam import (
        adam_step, fused_adam_update_multi)

    rng = np.random.RandomState(0)
    shapes = [(3, 5), (17,), (2, 2, 2), (1,)]
    ps = [jnp.asarray(rng.randn(*s).astype("f4")) for s in shapes]
    gs = [jnp.asarray(rng.randn(*s).astype("f4")) for s in shapes]
    ms = [jnp.asarray(rng.rand(*s).astype("f4")) for s in shapes]
    vs = [jnp.asarray(rng.rand(*s).astype("f4")) for s in shapes]
    lr, b1p, b2p = 0.01, 0.9, 0.999

    nps, nms, nvs = fused_adam_update_multi(ps, gs, ms, vs, lr, b1p, b2p)
    for i in range(len(shapes)):
        ep, em, ev = adam_step(ps[i], gs[i], ms[i], vs[i], lr, b1p, b2p,
                               use_fused=False)
        np.testing.assert_allclose(np.asarray(nps[i]), np.asarray(ep),
                                   rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(nms[i]), np.asarray(em),
                                   rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(nvs[i]), np.asarray(ev),
                                   rtol=2e-5, atol=1e-6)


def test_fused_adam_multi_weight_decay():
    """Decoupled wd inside the kernel == AdamW's p - lr*wd*p term."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.fused_adam import (
        adam_step, fused_adam_update_multi)
    rng = np.random.RandomState(1)
    p = jnp.asarray(rng.randn(4, 4).astype("f4"))
    g = jnp.asarray(rng.randn(4, 4).astype("f4"))
    m = jnp.zeros((4, 4), jnp.float32)
    v = jnp.zeros((4, 4), jnp.float32)
    lr, wd = 0.01, 0.1
    nps, _, _ = fused_adam_update_multi([p], [g], [m], [v], lr, 0.9,
                                        0.999, weight_decay=wd)
    ep, _, _ = adam_step(p, g, m, v, lr, 0.9, 0.999, use_fused=False)
    expect = np.asarray(ep) - lr * wd * np.asarray(p)
    np.testing.assert_allclose(np.asarray(nps[0]), expect, rtol=2e-5,
                               atol=1e-6)


def test_adam_optimizer_multi_tensor_path():
    """optimizer.AdamW(use_multi_tensor=True) trains identically to the
    per-tensor path (all params stepping together)."""
    from paddle_tpu import nn, optimizer

    def build():
        pt.seed(3)
        m = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2))
        return m

    x = pt.to_tensor(np.random.RandomState(2).randn(4, 6).astype("f4"))
    y = pt.to_tensor(np.random.RandomState(3).randn(4, 2).astype("f4"))

    results = []
    for multi in (False, True):
        m = build()
        o = optimizer.AdamW(learning_rate=1e-2,
                            parameters=m.parameters(),
                            weight_decay=0.01, use_multi_tensor=multi)
        for _ in range(4):
            loss = pt.nn.functional.mse_loss(m(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
        results.append([p.numpy().copy() for p in m.parameters()])
    for a, b in zip(*results):
        np.testing.assert_allclose(a, b, rtol=3e-5, atol=1e-6)
