"""Pallas fused kernels vs reference math (interpret mode on CPU,
SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.pallas import (layer_norm, softmax_cross_entropy,
                                   flash_attention, fused_adam_update)


def test_layer_norm_forward_matches():
    x = np.random.randn(32, 128).astype("f4")
    w = np.random.rand(128).astype("f4") + 0.5
    b = np.random.randn(128).astype("f4")
    out = layer_norm(pt.to_tensor(x), pt.to_tensor(w), pt.to_tensor(b))
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)


def test_layer_norm_grad_matches_xla():
    x = np.random.randn(16, 64).astype("f4")
    w = np.random.rand(64).astype("f4") + 0.5
    b = np.random.randn(64).astype("f4")

    tx = pt.to_tensor(x, stop_gradient=False)
    tw = pt.Parameter(w)
    tb = pt.Parameter(b)
    (layer_norm(tx, tw, tb) * pt.to_tensor(np.arange(64, dtype="f4"))
     ).sum().backward()

    tx2 = pt.to_tensor(x, stop_gradient=False)
    tw2 = pt.Parameter(w)
    tb2 = pt.Parameter(b)
    from paddle_tpu.nn import functional as F
    (F.layer_norm(tx2, 64, tw2, tb2) *
     pt.to_tensor(np.arange(64, dtype="f4"))).sum().backward()

    np.testing.assert_allclose(np.asarray(tx.grad), np.asarray(tx2.grad),
                               atol=2e-3)
    np.testing.assert_allclose(np.asarray(tw.grad), np.asarray(tw2.grad),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(tb.grad), np.asarray(tb2.grad),
                               rtol=2e-3, atol=2e-3)


def test_softmax_xent_matches_and_grads():
    logits = np.random.randn(24, 50).astype("f4")
    labels = np.random.randint(0, 50, (24,))

    t = pt.to_tensor(logits, stop_gradient=False)
    loss = softmax_cross_entropy(t, pt.to_tensor(labels))
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + \
        logits.max(-1)
    ref = lse - logits[np.arange(24), labels]
    np.testing.assert_allclose(loss.numpy().ravel(), ref, atol=1e-4)

    loss.mean().backward()
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(50, dtype="f4")[labels]
    ref_grad = (p - onehot) / 24
    np.testing.assert_allclose(np.asarray(t.grad), ref_grad, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_sdpa(causal):
    b, h, s, d = 2, 2, 64, 16
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                          causal=causal, block_q=32, block_k=32)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, atol=2e-3)


def test_flash_attention_backward():
    b, h, s, d = 1, 2, 32, 8
    rng = np.random.RandomState(1)
    q = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    k = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    v = pt.to_tensor(rng.randn(b, h, s, d).astype("f4"), stop_gradient=False)
    flash_attention(q, k, v, causal=True, block_q=16,
                    block_k=16).sum().backward()
    from paddle_tpu.nn import functional as F
    q2 = pt.to_tensor(q.numpy(), stop_gradient=False)
    k2 = pt.to_tensor(k.numpy(), stop_gradient=False)
    v2 = pt.to_tensor(v.numpy(), stop_gradient=False)
    F.scaled_dot_product_attention(q2, k2, v2,
                                   is_causal=True).sum().backward()
    np.testing.assert_allclose(np.asarray(q.grad), np.asarray(q2.grad),
                               atol=3e-3)
    np.testing.assert_allclose(np.asarray(k.grad), np.asarray(k2.grad),
                               atol=3e-3)
    np.testing.assert_allclose(np.asarray(v.grad), np.asarray(v2.grad),
                               atol=3e-3)


def test_fused_adam_matches_rule():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    p = rng.randn(37, 5).astype("f4")  # deliberately unaligned size
    g = rng.randn(37, 5).astype("f4")
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    b1p, b2p = b1, b2
    new_p, new_m, new_v = fused_adam_update(
        jnp.asarray(p), jnp.asarray(g), jnp.asarray(m), jnp.asarray(v),
        lr, b1p, b2p, beta1=b1, beta2=b2, eps=eps)
    m_ref = (1 - b1) * g
    v_ref = (1 - b2) * g * g
    p_ref = p - lr * (m_ref / (1 - b1p)) / (
        np.sqrt(v_ref / (1 - b2p)) + eps)
    np.testing.assert_allclose(np.asarray(new_p), p_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_m), m_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), v_ref, atol=1e-6)


def test_fused_adam_in_optimizer():
    from paddle_tpu import optimizer as opt
    w1 = pt.Parameter(np.ones((8, 4), "f4"))
    w2 = pt.Parameter(np.ones((8, 4), "f4"))
    o1 = opt.Adam(learning_rate=0.1, parameters=[w1], use_fused=True)
    o2 = opt.Adam(learning_rate=0.1, parameters=[w2])
    for o, w in ((o1, w1), (o2, w2)):
        (w * w).sum().backward()
        o.step()
        o.clear_grad()
    np.testing.assert_allclose(w1.numpy(), w2.numpy(), atol=1e-5)


def test_pallas_layer_norm_layer_flag():
    from paddle_tpu import nn
    ln = nn.LayerNorm(32, use_pallas=True)
    x = pt.to_tensor(np.random.randn(4, 32).astype("f4"))
    out = ln(x)
    o = out.numpy()
    np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-4)


def test_flash_attention_unaligned_seq():
    """Regression: tail K/V block must not be dropped (seq % block_k != 0)."""
    b, h, s, d = 1, 2, 40, 16
    rng = np.random.RandomState(3)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                          block_q=32, block_k=32)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, atol=2e-3)


def test_flash_attention_dropout_falls_back():
    b, h, s, d = 1, 1, 16, 8
    q = pt.to_tensor(np.random.randn(b, h, s, d).astype("f4"))
    out = flash_attention(q, q, q, dropout_p=0.5, training=True)
    assert out.shape == [b, h, s, d]
