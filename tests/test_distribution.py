"""Distribution tests vs closed forms / scipy (VERDICT r2 #5; reference:
python/paddle/fluid/tests/unittests/test_distributions.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distribution import (Uniform, Normal, Categorical,
                                     MultivariateNormalDiag)

from scipy import stats


class TestUniform:
    def test_sample_range_and_moments(self):
        u = Uniform(1.0, 3.0)
        s = u.sample((20000,), seed=7).numpy()
        assert s.min() >= 1.0 and s.max() <= 3.0
        np.testing.assert_allclose(s.mean(), 2.0, atol=0.05)

    def test_log_prob(self):
        u = Uniform(np.array([0.0, 1.0], "f4"), np.array([2.0, 5.0], "f4"))
        v = pt.to_tensor(np.array([1.0, 2.0], "f4"))
        got = u.log_prob(v).numpy()
        exp = [stats.uniform(0, 2).logpdf(1.0), stats.uniform(1, 4).logpdf(2.0)]
        np.testing.assert_allclose(got, exp, rtol=1e-5)
        outside = u.log_prob(pt.to_tensor(np.array([-1.0, 0.0], "f4")))
        assert np.all(np.isneginf(outside.numpy()))

    def test_entropy(self):
        u = Uniform(0.0, 4.0)
        np.testing.assert_allclose(u.entropy().numpy(),
                                   stats.uniform(0, 4).entropy(), rtol=1e-6)


class TestNormal:
    def test_sample_moments(self):
        n = Normal(2.0, 3.0)
        s = n.sample((40000,), seed=11).numpy()
        np.testing.assert_allclose(s.mean(), 2.0, atol=0.08)
        np.testing.assert_allclose(s.std(), 3.0, atol=0.08)

    def test_log_prob_and_entropy(self):
        loc = np.array([0.0, 1.5], "f4")
        sc = np.array([1.0, 0.5], "f4")
        n = Normal(loc, sc)
        v = np.array([0.3, 1.0], "f4")
        np.testing.assert_allclose(n.log_prob(pt.to_tensor(v)).numpy(),
                                   stats.norm(loc, sc).logpdf(v), rtol=1e-5)
        np.testing.assert_allclose(n.entropy().numpy(),
                                   stats.norm(loc, sc).entropy(), rtol=1e-5)

    def test_kl(self):
        a = Normal(0.0, 1.0)
        b = Normal(1.0, 2.0)
        # closed form: log(s2/s1) + (s1^2 + (l1-l2)^2) / (2 s2^2) - 1/2
        exp = np.log(2.0) + (1.0 + 1.0) / 8.0 - 0.5
        np.testing.assert_allclose(a.kl_divergence(b).numpy(), exp,
                                   rtol=1e-5)
        np.testing.assert_allclose(a.kl_divergence(a).numpy(), 0.0,
                                   atol=1e-6)


class TestCategorical:
    def test_sample_frequencies(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], "f4"))
        c = Categorical(logits)
        s = c.sample((30000,), seed=3).numpy()
        freq = np.bincount(s, minlength=3) / s.size
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)

    def test_entropy_log_prob_kl(self):
        p = np.array([0.1, 0.4, 0.5], "f4")
        q = np.array([0.3, 0.3, 0.4], "f4")
        c1 = Categorical(np.log(p))
        c2 = Categorical(np.log(q))
        np.testing.assert_allclose(c1.entropy().numpy(),
                                   stats.entropy(p), rtol=1e-5)
        np.testing.assert_allclose(
            c1.log_prob(pt.to_tensor(np.array([2], "i4"))).numpy(),
            [np.log(0.5)], rtol=1e-5)
        np.testing.assert_allclose(c1.kl_divergence(c2).numpy(),
                                   stats.entropy(p, q), rtol=1e-4)


class TestMVNDiag:
    def test_log_prob_vs_scipy(self):
        loc = np.array([1.0, -1.0, 0.5], "f4")
        diag = np.array([0.5, 2.0, 1.0], "f4")
        d = MultivariateNormalDiag(loc, diag)
        v = np.array([0.3, 0.0, 1.0], "f4")
        exp = stats.multivariate_normal(loc, np.diag(diag ** 2)).logpdf(v)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(v)).numpy(),
                                   exp, rtol=1e-4)

    def test_entropy_and_kl(self):
        loc = np.array([0.0, 0.0], "f4")
        diag = np.array([1.0, 2.0], "f4")
        d = MultivariateNormalDiag(loc, diag)
        exp = stats.multivariate_normal(loc, np.diag(diag ** 2)).entropy()
        np.testing.assert_allclose(d.entropy().numpy(), exp, rtol=1e-5)
        d2 = MultivariateNormalDiag(np.array([1.0, 0.0], "f4"),
                                    np.array([2.0, 1.0], "f4"))
        # KL via the general gaussian formula with diagonal covs
        s1, s2 = diag ** 2, np.array([4.0, 1.0], "f4")
        mu = np.array([1.0, 0.0]) - loc
        exp_kl = 0.5 * (np.sum(s1 / s2) + np.sum(mu ** 2 / s2) - 2 +
                        np.log(np.prod(s2) / np.prod(s1)))
        np.testing.assert_allclose(d.kl_divergence(d2).numpy(), exp_kl,
                                   rtol=1e-5)

    def test_matrix_scale_accepted(self):
        # reference passes a diagonal *matrix*; both forms must agree
        loc = np.array([0.0, 1.0], "f4")
        diag = np.array([1.5, 0.5], "f4")
        a = MultivariateNormalDiag(loc, diag)
        b = MultivariateNormalDiag(loc, np.diag(diag))
        v = pt.to_tensor(np.array([0.2, 0.8], "f4"))
        np.testing.assert_allclose(a.log_prob(v).numpy(),
                                   b.log_prob(v).numpy(), rtol=1e-6)


def test_seeded_reproducible():
    n = Normal(0.0, 1.0)
    s1 = n.sample((8,), seed=5).numpy()
    s2 = n.sample((8,), seed=5).numpy()
    np.testing.assert_array_equal(s1, s2)


def test_global_key_advances():
    pt.seed(0)
    n = Normal(0.0, 1.0)
    s1 = n.sample((8,)).numpy()
    s2 = n.sample((8,)).numpy()
    assert np.abs(s1 - s2).max() > 1e-6


def test_fluid_layers_export():
    from paddle_tpu.fluid import layers as FL
    assert FL.Normal is Normal and FL.Categorical is Categorical
