"""RNN cells/driver + control flow (SURVEY §2 #9/#10)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, ops


def test_lstm_cell_step():
    cell = nn.LSTMCell(8, 16)
    x = pt.to_tensor(np.random.randn(4, 8).astype("f4"))
    h, c = cell.get_initial_states(4)
    out, (h2, c2) = cell(x, (h, c))
    assert out.shape == [4, 16] and c2.shape == [4, 16]


def test_gru_cell_matches_manual():
    cell = nn.GRUCell(4, 6)
    x = np.random.randn(2, 4).astype("f4")
    h = np.zeros((2, 6), "f4")
    out, _ = cell(pt.to_tensor(x), pt.to_tensor(h))
    # manual
    wi, wh = cell.weight_ih.numpy(), cell.weight_hh.numpy()
    bi, bh = cell.bias_ih.numpy(), cell.bias_hh.numpy()
    gi, gh = x @ wi + bi, h @ wh + bh
    sig = lambda v: 1 / (1 + np.exp(-v))
    r = sig(gi[:, :6] + gh[:, :6])
    z = sig(gi[:, 6:12] + gh[:, 6:12])
    n = np.tanh(gi[:, 12:] + r * gh[:, 12:])
    ref = (1 - z) * n + z * h
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)


def test_rnn_scan_driver_matches_stepwise():
    pt.seed(1)
    cell = nn.LSTMCell(4, 8)
    xs = np.random.randn(2, 5, 4).astype("f4")  # batch-major [B,T,F]
    rnn = nn.RNN(cell)
    ys, (h, c) = rnn(pt.to_tensor(xs))
    assert ys.shape == [2, 5, 8]
    # stepwise reference
    state = cell.get_initial_states(2)
    outs = []
    for t in range(5):
        out, state = cell(pt.to_tensor(xs[:, t]), state)
        outs.append(out.numpy())
    np.testing.assert_allclose(ys.numpy(), np.stack(outs, 1), atol=1e-5)
    np.testing.assert_allclose(h.numpy(), outs[-1], atol=1e-5)


def test_rnn_gradients_flow():
    cell = nn.GRUCell(4, 8)
    rnn = nn.RNN(cell)
    xs = pt.to_tensor(np.random.randn(2, 6, 4).astype("f4"),
                      stop_gradient=False)
    ys, _ = rnn(xs)
    ys.sum().backward()
    assert xs.grad is not None
    assert cell.weight_ih.grad is not None


def test_multilayer_bidirectional_lstm():
    lstm = nn.LSTM(4, 8, num_layers=2, direction="bidirectional")
    xs = pt.to_tensor(np.random.randn(3, 7, 4).astype("f4"))
    ys, finals = lstm(xs)
    assert ys.shape == [3, 7, 16]
    assert len(finals) == 2


def test_cond_eager_and_traced():
    # eager concrete: python branch
    out = ops.cond(pt.to_tensor(True), lambda: pt.to_tensor(1.0),
                   lambda: pt.to_tensor(2.0))
    assert float(out.numpy()) == 1.0

    # traced: inside to_static
    from paddle_tpu import jit

    @jit.to_static
    def f(x):
        return ops.cond(x.sum() > 0,
                        lambda v: v * 2.0,
                        lambda v: v - 1.0, operands=(x,))

    a = f(pt.to_tensor(np.array([1.0, 2.0], "f4")))
    np.testing.assert_allclose(a.numpy(), [2.0, 4.0])
    b = f(pt.to_tensor(np.array([-5.0, 1.0], "f4")))
    np.testing.assert_allclose(b.numpy(), [-6.0, 0.0])


def test_while_loop_eager():
    i = pt.to_tensor(0)
    s = pt.to_tensor(0.0)
    i2, s2 = ops.while_loop(lambda i, s: i < 5,
                            lambda i, s: (i + 1, s + 2.0), [i, s])
    assert int(i2.numpy()) == 5 and float(s2.numpy()) == 10.0


def test_while_loop_traced():
    from paddle_tpu import jit

    @jit.to_static
    def f(n):
        i = pt.zeros((), "int32")
        acc = pt.zeros((), "float32")
        i2, acc2 = ops.while_loop(lambda i, a: i < n,
                                  lambda i, a: (i + 1, a + 3.0), [i, acc])
        return acc2

    out = f(pt.to_tensor(np.asarray(4, "i4")))
    assert float(out.numpy()) == 12.0


def test_switch_case_and_case():
    def b0(): return pt.to_tensor(10.0)
    def b1(): return pt.to_tensor(20.0)
    def bd(): return pt.to_tensor(-1.0)
    assert float(ops.switch_case(pt.to_tensor(1), [b0, b1],
                                 default=bd).numpy()) == 20.0
    assert float(ops.switch_case(pt.to_tensor(7), [b0, b1],
                                 default=bd).numpy()) == -1.0
    out = ops.case([(pt.to_tensor(False), b0), (pt.to_tensor(True), b1)],
                   default=bd)
    assert float(out.numpy()) == 20.0


def test_inference_predictor():
    from paddle_tpu.inference import Predictor, Config
    from paddle_tpu.models import LeNet
    m = LeNet()
    pred = Predictor(m)
    x = np.random.rand(2, 1, 28, 28).astype("f4")
    out = pred.run(x)
    assert out.shape == (2, 10)
    ref = m.eval()(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # second call reuses the compiled executable
    assert len(pred._compiled) == 1
    pred.run(x)
    assert len(pred._compiled) == 1


def test_native_dataloader_epoch():
    from paddle_tpu import io
    ds = io.TensorDataset(np.arange(50, dtype="f4").reshape(50, 1),
                          np.arange(50, dtype="i4"))
    dl = io.DataLoader(ds, batch_size=8, shuffle=True, seed=3)
    assert dl._native_epoch is not None
    seen = [int(v) for _, yb in dl for v in yb]
    assert sorted(seen) == list(range(50))
    seen2 = [int(v) for _, yb in dl for v in yb]
    assert sorted(seen2) == list(range(50)) and seen != seen2


def test_rnn_sequence_length_masks_padding():
    """Padding steps must not affect outputs or final state."""
    pt.seed(2)
    cell = nn.LSTMCell(3, 6)
    rnn = nn.RNN(cell)
    xs = np.random.randn(2, 8, 3).astype("f4")
    lens = np.array([3, 8])
    ys, (h, c) = rnn(pt.to_tensor(xs), sequence_length=pt.to_tensor(lens))
    # row 0 outputs beyond t=3 are zero
    assert np.allclose(ys.numpy()[0, 3:], 0.0)
    # final state of row 0 equals running only its 3 real steps
    ys3, (h3, _) = rnn(pt.to_tensor(xs[:1, :3]))
    np.testing.assert_allclose(h.numpy()[0], h3.numpy()[0], atol=1e-5)


def test_reverse_rnn_sequence_length():
    """Reverse RNN must start each row at its last REAL step."""
    pt.seed(3)
    cell = nn.GRUCell(3, 5)
    rnn_rev = nn.RNN(cell, is_reverse=True)
    xs = np.random.randn(2, 6, 3).astype("f4")
    lens = np.array([2, 6])
    ys, _ = rnn_rev(pt.to_tensor(xs), sequence_length=pt.to_tensor(lens))
    # row 0: equivalent to reversing just its 2-step prefix
    ys_ref, _ = rnn_rev(pt.to_tensor(xs[:1, :2]))
    np.testing.assert_allclose(ys.numpy()[0, :2], ys_ref.numpy()[0],
                               atol=1e-5)


def test_case_traced_requires_default():
    from paddle_tpu import jit

    @jit.to_static
    def f(x):
        return ops.case([(x.sum() > 0, lambda: x * 2.0)])

    with pytest.raises(ValueError, match="default"):
        f(pt.to_tensor(np.array([1.0], "f4")))


def test_sequence_mask_traced_requires_maxlen():
    import paddle_tpu.fluid as fluid
    from paddle_tpu import jit

    @jit.to_static
    def f(lens):
        return fluid.layers.sequence_mask(lens)

    with pytest.raises(ValueError, match="maxlen"):
        f(pt.to_tensor(np.array([2, 3])))

    # explicit maxlen works under trace
    @jit.to_static
    def g(lens):
        return fluid.layers.sequence_mask(lens, maxlen=4)

    np.testing.assert_array_equal(
        g(pt.to_tensor(np.array([2, 3]))).numpy(),
        [[1, 1, 0, 0], [1, 1, 1, 0]])


def test_dataloader_early_break_restarts_epoch():
    from paddle_tpu import io
    ds = io.TensorDataset(np.arange(40, dtype="f4").reshape(40, 1),
                          np.arange(40, dtype="i4"))
    dl = io.DataLoader(ds, batch_size=8, shuffle=False)
    for i, b in enumerate(dl):
        if i == 1:
            break
    # next iteration must be a FULL fresh epoch
    seen = [int(v) for _, yb in dl for v in yb]
    assert len(seen) == 40 and sorted(seen) == list(range(40))


def test_nce_custom_dist():
    freqs = np.ones(100, "f4")
    freqs[:10] = 10.0
    freqs /= freqs.sum()
    nce = nn.NCE(100, 8, num_neg_samples=5, custom_dist=freqs)
    x = pt.to_tensor(np.random.randn(4, 8).astype("f4"))
    loss = nce(x, pt.to_tensor(np.array([0, 1, 50, 99]))).mean()
    assert np.isfinite(float(loss.numpy()))
