"""Worker script for test_multiprocess_launch.py — run through
`paddle_tpu.distributed.launch --nproc_per_node 2`.

Each process owns 4 virtual CPU devices; jax.distributed stitches them
into one 8-device global mesh (the same code path a multi-host TPU pod's
DCN uses). Trains a tiny regression data-parallel: every process feeds
its LOCAL batch shard, gradients sync through the jitted step's
collectives, and the final params (gathered) must be identical on every
rank — written to a per-rank JSON for the test to compare."""
import json
import os
import sys

# forced-CPU child: must happen before jax initializes a backend
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from paddle_tpu.distributed import init_parallel_env  # noqa: E402

init_parallel_env()  # consumes COORDINATOR_ADDRESS / trainer env

import jax  # noqa: E402
import paddle_tpu as pt  # noqa: E402
from paddle_tpu import nn, optimizer as opt, jit  # noqa: E402
from paddle_tpu.nn import functional as F  # noqa: E402
from paddle_tpu.parallel.fleet import Fleet  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

rank = jax.process_index()
assert jax.device_count() == 8 and jax.local_device_count() == 4

fleet = Fleet().init(mesh_shape={"dp": 8})
pt.seed(0)
model = fleet.distributed_model(nn.Linear(4, 2))
o = fleet.distributed_optimizer(
    opt.SGD(learning_rate=0.1, parameters=model.parameters()))

rng = np.random.RandomState(0)          # SAME data on both ranks...
x_global = rng.randn(16, 4).astype("f4")
y_global = (x_global @ rng.randn(4, 2).astype("f4"))
# ...but each process PLACES only its half (8 rows) — the multi-host
# feeding pattern: make_array_from_process_local_data builds the global
# sharded batch from per-process shards
mesh = fleet.mesh
sh = NamedSharding(mesh, P("dp"))
lo = rank * 8
tx = pt.Tensor(jax.make_array_from_process_local_data(
    sh, x_global[lo:lo + 8]))
ty = pt.Tensor(jax.make_array_from_process_local_data(
    sh, y_global[lo:lo + 8]))


def step(x, y):
    loss = F.mse_loss(model(x), y)
    loss.backward()
    o.step()
    o.clear_grad()
    return loss


cstep = jit.to_static(step, models=[model], optimizers=[o])
losses = [float(np.asarray(jax.device_get(cstep(tx, ty).data)))
          for _ in range(4)]

w = np.asarray(jax.device_get(model.weight.data)).tolist()
out = {"rank": rank, "losses": losses, "weight": w}
with open(os.environ["MULTIPROC_OUT"] + f".{rank}", "w") as f:
    json.dump(out, f)
print(f"[rank {rank}] done losses={losses}", flush=True)
