"""IO: save/load, checkpoints, DataLoader (SURVEY §4)."""
import os
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, io, optimizer as opt


def test_save_load_state_dict(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    path = str(tmp_path / "model.pdparams")
    io.save(m.state_dict(), path)
    loaded = io.load(path)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    m2.set_state_dict(loaded)
    for (k, v), (k2, v2) in zip(sorted(m.state_dict().items()),
                                sorted(m2.state_dict().items())):
        np.testing.assert_allclose(v.numpy(), v2.numpy())


def test_save_load_dygraph_roundtrip(tmp_path):
    m = nn.Linear(3, 3)
    o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    m(pt.to_tensor(np.ones((2, 3), "f4"))).mean().backward()
    o.step()
    io.save_dygraph(m.state_dict(), str(tmp_path / "ck"))
    params, _ = io.load_dygraph(str(tmp_path / "ck"))
    assert params is not None and "weight" in params


def test_inference_model_roundtrip(tmp_path):
    from paddle_tpu.models import LeNet
    m = LeNet()
    x = np.random.randn(2, 1, 28, 28).astype("f4")
    ref = m.eval()(pt.to_tensor(x)).numpy()
    io.save_inference_model(str(tmp_path / "infer"), m)
    m2 = io.load_inference_model(str(tmp_path / "infer"))
    out = m2(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_checkpoint_manager(tmp_path):
    m = nn.Linear(2, 2)
    o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    cm = io.CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
    for step in [10, 20, 30]:
        m(pt.to_tensor(np.ones((1, 2), "f4"))).mean().backward()
        o.step(); o.clear_grad()
        cm.save(step, model=m, optimizer=o)
    assert cm.latest_step() == 30
    # only last 2 kept
    assert cm._steps() == [20, 30]
    w_before = m.weight.numpy().copy()
    m.weight.set_value(np.zeros_like(w_before))
    state = cm.restore(model=m, optimizer=o)
    assert state["step"] == 30
    np.testing.assert_allclose(m.weight.numpy(), w_before)


def test_dataloader_batching_and_shuffle():
    x = np.arange(100, dtype="f4").reshape(100, 1)
    y = np.arange(100, dtype="i4")
    ds = io.TensorDataset(x, y)
    dl = io.DataLoader(ds, batch_size=16, shuffle=False, drop_last=True)
    batches = list(dl)
    assert len(batches) == 6
    assert batches[0][0].shape == (16, 1)
    np.testing.assert_allclose(batches[0][1], np.arange(16))

    dl2 = io.DataLoader(ds, batch_size=16, shuffle=True, seed=0)
    b1 = list(dl2)
    assert not np.allclose(b1[0][1], np.arange(16))
    # epoch 2 reshuffles differently
    b2 = list(dl2)
    assert not np.allclose(b1[0][1], b2[0][1])


def test_dataloader_prefetch_thread():
    ds = io.TensorDataset(np.random.rand(64, 3).astype("f4"))
    dl = io.DataLoader(ds, batch_size=8, num_workers=1, prefetch_factor=2)
    total = sum(b[0].shape[0] for b in dl)
    assert total == 64


def test_reader_decorators():
    def reader():
        for i in range(10):
            yield (np.float32(i),)
    br = io.batch_reader(reader, 3)
    batches = list(br())
    assert len(batches) == 4
    sr = io.shuffle_reader(reader, buf_size=10, seed=1)
    vals = [v[0] for v in sr()]
    assert sorted(vals) == list(range(10))


# ---------------------------------------------------------------------------
# multiprocess DataLoader (VERDICT r3 #6; reference:
# fluid/dataloader/dataloader_iter.py)


class _TransformDS:
    """Python-transform dataset: CPU-bound work per item (the GIL-bound
    decode/augment shape the worker processes exist for)."""

    def __init__(self, n=64, work=2000):
        self.n = n
        self.work = work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        x = rng.rand(self.work).astype("f4")
        for _ in range(30):  # burn python+numpy cycles
            x = np.sqrt(x * x + 1e-3)
        return x[:16], np.int32(i)


def test_dataloader_multiprocess_order_and_content():
    ds = _TransformDS(n=23, work=64)
    ref = [ds[i] for i in range(len(ds))]
    loader = io.DataLoader(ds, batch_size=4, shuffle=False,
                           num_workers=3, use_native=False)
    seen = []
    for xb, ib in loader:
        assert xb.shape[1] == 16
        seen.extend(int(v) for v in ib)
        for row, i in zip(xb, ib):
            np.testing.assert_allclose(row, ref[int(i)][0], rtol=1e-6)
    assert seen == list(range(23))  # order preserved, nothing dropped


def test_dataloader_multiprocess_worker_error_surfaces():
    class Bad:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom at 5")
            return np.zeros(3, "f4")

    loader = io.DataLoader(Bad(), batch_size=2, num_workers=2,
                           use_native=False)
    with pytest.raises(ValueError, match="boom"):
        for _ in loader:
            pass


class _SleepDS:
    """Items block on a GIL-releasing sleep, not CPU: worker overlap is
    then a property of the loader's concurrency alone, independent of
    how many cores the host has."""

    def __init__(self, n=8, delay=0.25):
        self.n = n
        self.delay = delay

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        import time
        time.sleep(self.delay)
        return np.full(4, i, "f4"), np.int32(i)


def test_dataloader_workers_overlap_deterministically():
    """num_workers=4 must overlap item fetches (the r3 verdict's
    acceptance bar for num_workers). Deterministic small-N form: 8
    items that each sleep 0.5s have a hard 4.0s serial floor; any
    loader that finishes well under that floor is provably running
    items concurrently. Sleeps release the GIL, so this holds even on
    a 1-core host — no core-count gate, no skip. The 0.75-floor bar
    leaves 4×delay = 2s of slack for worker startup, which is what a
    loaded CI box actually eats (measured ~1.1s worst)."""
    import time
    n, delay = 8, 0.5
    ds = _SleepDS(n=n, delay=delay)
    # warm fork/page-cache so startup cost doesn't count against overlap
    warm = io.DataLoader(_SleepDS(n=4, delay=0.01), batch_size=2,
                         num_workers=4, use_native=False)
    for _ in warm:
        pass
    loader = io.DataLoader(ds, batch_size=2, num_workers=4,
                           use_native=False)
    t0 = time.perf_counter()
    seen = []
    for xb, ib in loader:
        seen.extend(int(v) for v in ib)
    elapsed = time.perf_counter() - t0
    assert seen == list(range(n))  # order preserved, nothing dropped
    serial_floor = n * delay
    assert elapsed < 0.75 * serial_floor, (
        f"{elapsed:.2f}s vs {serial_floor:.2f}s serial floor — workers "
        "are not overlapping item fetches")


def test_batch_sampler_semantics():
    """BatchSampler: drop_last, shuffle determinism per (seed, epoch)."""
    ds = io.TensorDataset(np.arange(10, dtype="f4"))
    s = io.BatchSampler(ds, batch_size=3, drop_last=True)
    batches = list(s)
    assert [len(b) for b in batches] == [3, 3, 3]
    s2 = io.BatchSampler(ds, batch_size=3, drop_last=False)
    assert [len(b) for b in list(s2)] == [3, 3, 3, 1]

    a = io.BatchSampler(ds, batch_size=4, shuffle=True, seed=7)
    b = io.BatchSampler(ds, batch_size=4, shuffle=True, seed=7)
    ep0 = [list(x) for x in a]
    assert ep0 == [list(x) for x in b]  # same (seed, epoch) same order
    # __iter__ advances the epoch: the next pass reshuffles...
    ep1 = [list(x) for x in a]
    assert ep1 != ep0
    # ...and set_epoch pins it deterministically
    b.set_epoch(1)
    assert [list(x) for x in b] == ep1


def test_iterable_dataset_loader():
    class Gen(io.IterableDataset):
        def __iter__(self):
            for i in range(7):
                yield np.float32(i), np.int32(i % 2)

    loader = io.DataLoader(Gen(), batch_size=3, use_native=False)
    got = [xb for xb, _ in loader]
    total = sum(x.shape[0] for x in got)
    assert total == 7
    np.testing.assert_allclose(got[0].ravel(), [0, 1, 2], atol=0)


def test_static_save_load_vars(tmp_path):
    """save_vars/load_vars/set_program_state round-trip static-mode
    parameters (reference io.py surface)."""
    import paddle_tpu as pt
    from paddle_tpu import static

    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", (None, 4), "float32")
            y = pt.fluid.layers.fc(x, size=3)
        exe = static.Executor()
        exe.run(static.default_startup_program())

        params = io.get_program_parameter(prog)
        assert len(params) >= 1
        pers = io.get_program_persistable_vars(prog)
        assert len(pers) >= 1

        d = str(tmp_path / "vars")
        io.save_vars(exe, dirname=d, main_program=prog,
                     filename="all.npz")
        before = {v.name: np.asarray(v.numpy()).copy() for v in params}
        # clobber, then restore
        for v in params:
            v.set_value(np.zeros(v.shape, "f4"))
        io.load_vars(exe, dirname=d, main_program=prog,
                     filename="all.npz")
        for v in params:
            np.testing.assert_allclose(v.numpy(), before[v.name],
                                       atol=0)

        # set_program_state: dict -> program params
        state = {k: v * 2 for k, v in before.items()}
        io.set_program_state(prog, state)
        for v in params:
            np.testing.assert_allclose(v.numpy(), before[v.name] * 2,
                                       atol=0)
    finally:
        pt.disable_static()


def test_native_batcher_direct():
    """Direct contract of the C++ batcher (csrc/core.cpp via ctypes):
    epoch iteration covers every row exactly once (shuffled), gather
    returns rows in the requested order, and dtypes survive."""
    from paddle_tpu.io.native import NativeBatcher

    arrs = [np.arange(20, dtype="f4").reshape(10, 2),
            np.arange(10, dtype="i4")]
    b = NativeBatcher(arrs, batch_size=4, shuffle=True, drop_last=False,
                      seed=1)
    seen = []
    sizes = []
    for xb, yb in b:
        assert xb.dtype == np.float32 and yb.dtype == np.int32
        np.testing.assert_allclose(xb[:, 0], yb * 2.0, atol=0)
        seen.extend(yb.tolist())
        sizes.append(len(yb))
    assert sorted(seen) == list(range(10))
    assert sizes == [4, 4, 2]

    g = NativeBatcher(arrs).gather([3, 1, 3])
    np.testing.assert_allclose(g[0], arrs[0][[3, 1, 3]], atol=0)
    np.testing.assert_array_equal(g[1], [3, 1, 3])

    # drop_last drops the ragged tail
    b2 = NativeBatcher(arrs, batch_size=4, shuffle=False, drop_last=True,
                       seed=0)
    assert [len(y) for _, y in b2] == [4, 4]
