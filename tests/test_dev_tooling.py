"""Dev-tooling tail (VERDICT r3 #8 + Missing #3/#4/#5): install_check,
Program graphviz dump, evaluator facade, model_stat/memory_usage/
op_frequence, PS-async trainer descriptors, data generator protocol."""
import io as _io
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, static, optimizer as opt, fluid


def test_install_check_runs(capsys):
    assert fluid.install_check.run_check() is True
    out = capsys.readouterr().out
    assert "works" in out and "compiled step ok" in out
    assert "data-parallel step ok on 8 devices" in out  # CPU mesh


def _tiny_program():
    pt.seed(0)
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 3], "float32")
            m = nn.Linear(3, 2)
            y = m(x)
            loss = y.square().mean()
    finally:
        pt.disable_static()
    return prog, loss


def test_graphviz_dump_and_pprint(tmp_path):
    prog, _ = _tiny_program()
    p = str(tmp_path / "g.dot")
    dot = fluid.debugger.draw_block_graphviz(prog, path=p)
    text = open(p).read()
    assert text == dot
    assert dot.startswith("digraph") and "shape=box" in dot
    assert dot.count("->") >= 2  # data edges exist
    code = fluid.debugger.pprint_program_codes(prog)
    assert "= " in code and len(code.splitlines()) >= 2
    # net_drawer front
    dot2 = fluid.net_drawer.draw_graph(main_program=prog)
    assert dot2.startswith("digraph")


def test_evaluator_facades():
    with pytest.warns(DeprecationWarning):
        ce = fluid.evaluator.ChunkEvaluator()
    ce.update(10, 8, 6)
    p, r, f1 = ce.eval()
    assert abs(p - 0.6) < 1e-9 and abs(r - 0.75) < 1e-9
    ce.reset()
    assert ce.eval() == (0, 0, 0.0)

    with pytest.warns(DeprecationWarning):
        ed = fluid.evaluator.EditDistance()
    ed.update([0.0, 2.0, 1.0])
    avg, err = ed.eval()
    assert abs(avg - 1.0) < 1e-9 and abs(err - 2 / 3) < 1e-9


def test_model_stat_summary_and_memory():
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    info = fluid.contrib.summary(m, input_spec=np.zeros((4, 8), "f4"))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    # Linear FLOPs = 2 * out_elems * in_features
    assert info["total_flops"] == 2 * (4 * 16 * 8 + 4 * 2 * 16)

    low, high = fluid.contrib.memory_usage(m, batch_size=4)
    assert 0 < low < high

    prog, _ = _tiny_program()
    plow, phigh = fluid.contrib.memory_usage(prog, batch_size=4)
    assert 0 < plow < phigh


def test_op_frequence_program_and_jaxpr():
    prog, _ = _tiny_program()
    uni, adj = fluid.contrib.op_freq_statistic(prog)
    assert sum(uni.values()) == len(prog.global_block().ops)
    assert all("->" in k for k in adj)

    import jax.numpy as jnp
    freq2 = fluid.contrib.op_freq_statistic(
        lambda x: jnp.tanh(x) @ x.T + 1.0, np.ones((3, 3), "f4"))
    assert freq2["tanh"] == 1 and freq2["dot_general"] == 1


def test_trainer_factory_and_communicator():
    tf = fluid.TrainerFactory()
    t = tf._create_trainer()
    assert isinstance(t, fluid.MultiTrainer)
    t2 = tf._create_trainer({"trainer": "DistMultiTrainer",
                             "device_worker": "DownpourSGD"})
    assert isinstance(t2, fluid.DistMultiTrainer)
    t2.set_fetch_var_and_info(["loss"], ["train loss"], 10)
    assert t2._desc()["worker"] == "DownpourSGD"
    with pytest.raises(ValueError):
        tf._create_trainer({"trainer": "NoSuch"})

    c = fluid.Communicator()
    assert not c.is_running()
    c.start()
    assert c.is_running()
    c.stop()
    assert not c.is_running()
    with pytest.warns(UserWarning, match="geo"):
        fluid.Communicator(kwargs={"geo_need_push_nums": 400})


def test_data_feed_desc_roundtrip():
    proto = '''
    name: "MultiSlotDataFeed"
    batch_size: 2
    multi_slot_desc {
      slots { name: "words" type: "uint64" is_dense: false is_used: false }
      slots { name: "label" type: "uint64" is_dense: false is_used: false }
    }
    '''
    desc = fluid.DataFeedDesc(proto)
    assert [s["name"] for s in desc.slots] == ["words", "label"]
    desc.set_batch_size(128)
    desc.set_use_slots(["words"])
    desc.set_dense_slots(["label"])
    assert desc.proto_desc["batch_size"] == 128
    assert desc.used_slots() == ["words"]
    assert next(s for s in desc.slots if s["name"] == "label")["is_dense"]
    text = desc.desc()
    # re-parse what we serialized
    again = fluid.DataFeedDesc(text)
    assert again.proto_desc["batch_size"] == 128
    assert again.used_slots() == ["words"]


def test_multi_slot_data_generator_protocol(monkeypatch, capsys):
    from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

    class MyGen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("words", [1, 2, 3]), ("label", [0])]
            return gen

    g = MyGen()
    g.set_batch(1)
    monkeypatch.setattr(sys, "stdin", _io.StringIO("x\ny\n"))
    g.run_from_stdin()
    out = capsys.readouterr().out
    # per line: "3 1 2 3 1 0" (count-prefixed slots, space-joined)
    assert out.splitlines() == ["3 1 2 3 1 0", "3 1 2 3 1 0"]
