"""Tape autograd correctness vs jax.grad (SURVEY §4)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt


def test_simple_grad_matches_jax():
    x = pt.to_tensor(np.random.randn(4, 3).astype("f4"), stop_gradient=False)
    w = pt.Parameter(np.random.randn(3, 2).astype("f4"))
    loss = pt.matmul(x, w).square().mean()
    loss.backward()
    ref = jax.grad(lambda w_: jnp.mean(jnp.square(x.data @ w_)))(w.data)
    np.testing.assert_allclose(w.grad, ref, atol=1e-5)
    ref_x = jax.grad(lambda x_: jnp.mean(jnp.square(x_ @ w.data)))(x.data)
    np.testing.assert_allclose(x.grad, ref_x, atol=1e-5)


def test_grad_accumulation():
    w = pt.Parameter(np.ones((3,), "f4"))
    for _ in range(3):
        (w * 2.0).sum().backward()
    np.testing.assert_allclose(w.grad, 6.0 * np.ones(3), atol=1e-6)
    w.clear_gradient()
    assert w.grad is None


def test_branching_graph():
    w = pt.Parameter(np.array([2.0], "f4"))
    a = w * 3.0
    b = a * a + a
    b.sum().backward()
    # d/dw (9w^2 + 3w) = 18w + 3 = 39
    np.testing.assert_allclose(w.grad, [39.0], atol=1e-5)


def test_no_grad():
    w = pt.Parameter(np.ones((3,), "f4"))
    with pt.no_grad():
        y = (w * 2.0).sum()
    assert y._tape_node is None
    y2 = (w * 2.0).sum()
    assert y2._tape_node is not None


def test_stop_gradient_blocks():
    w = pt.Parameter(np.ones((3,), "f4"))
    y = (w * 2.0).detach()
    z = (y * 3.0).sum()
    z.backward()
    assert w.grad is None


def test_functional_grad_api():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    g = pt.autograd.grad(y, x, retain_graph=False)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], atol=1e-6)
    assert x.grad is None  # paddle.grad must not touch accumulators


def test_multi_output_op_grad():
    x = pt.to_tensor(np.random.randn(5, 4).astype("f4"), stop_gradient=False)
    vals, idx = pt.topk(x, k=2)
    vals.sum().backward()
    assert x.grad is not None
    assert x.grad.shape == (5, 4)


def test_second_backward_without_retain_raises():
    w = pt.Parameter(np.ones((2,), "f4"))
    y = (w * 2.0).sum()
    y.backward()
    with pytest.raises(RuntimeError, match="freed"):
        y.backward()
    # with retain_graph the second backward accumulates
    y2 = (w * 2.0).sum()
    w.clear_gradient()
    y2.backward(retain_graph=True)
    y2.backward()
    np.testing.assert_allclose(np.asarray(w.grad), 4.0 * np.ones(2))
