"""serving.sampling (PR 17): the jit-safe filter pipeline (greedy /
temperature / top-k / top-p as batch-shaped knobs), counter-based PRNG
key determinism, Gumbel-max draw statistics, and the SamplingParams /
resolve() surface. All CPU, all fast."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.serving import sampling


def _filt(logits, temps, top_ks, top_ps):
    return np.asarray(sampling.filter_logits(
        jnp.asarray(logits, jnp.float32),
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(top_ks, jnp.int32),
        jnp.asarray(top_ps, jnp.float32)))


# ---------------------------------------------------------------------------
# SamplingParams / resolve


def test_params_validation_and_resolve():
    p = sampling.SamplingParams(temperature=0.7, top_k=5, top_p=0.9,
                                seed=3)
    assert not p.greedy
    assert sampling.SamplingParams().greedy
    with pytest.raises(ValueError):
        sampling.SamplingParams(top_p=0.0)
    with pytest.raises(ValueError):
        sampling.SamplingParams(top_p=1.5)
    with pytest.raises(ValueError):
        sampling.SamplingParams(seed=-1)
    # dict / params / None forms; seed override; defensive copy
    assert sampling.resolve(None).greedy
    d = sampling.resolve({"temperature": 1.0, "top_k": 4}, seed=9)
    assert d.top_k == 4 and d.seed == 9
    r = sampling.resolve(p, seed=11)
    assert r == sampling.SamplingParams(0.7, 5, 0.9, 11)
    assert p.seed == 3            # the original is untouched
    with pytest.raises(TypeError):
        sampling.resolve("greedy")


# ---------------------------------------------------------------------------
# the filter pipeline


def test_greedy_row_is_onehot_argmax():
    logits = np.array([[0.1, 2.0, -1.0, 2.0],    # tie -> lowest id
                       [3.0, 0.0, 0.0, 0.0]], np.float32)
    out = _filt(logits, [0.0, -1.0], [0, 0], [1.0, 1.0])
    assert (out[0] > sampling.NEG / 2).tolist() == [False, True, False,
                                                    False]
    assert (out[1] > sampling.NEG / 2).tolist() == [True, False, False,
                                                    False]


def test_top_k_1_equals_greedy_choice():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(6, 16)).astype(np.float32)
    k1 = _filt(logits, np.ones(6), np.ones(6, np.int32), np.ones(6))
    kept = k1 > sampling.NEG / 2
    assert (kept.sum(axis=1) == 1).all()
    assert (kept.argmax(axis=1) == logits.argmax(axis=1)).all()
    # and the draw from a single-survivor row is deterministic
    tok = np.asarray(sampling.sample_from_filtered(
        jnp.asarray(k1), jnp.arange(6, dtype=jnp.uint32),
        jnp.zeros(6, jnp.int32)))
    assert (tok == logits.argmax(axis=1)).all()


def test_top_p_1_is_plain_temperature():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(4, 12)).astype(np.float32)
    out = _filt(logits, 2.0 * np.ones(4), np.zeros(4, np.int32),
                np.ones(4))
    np.testing.assert_allclose(out, logits / 2.0, rtol=1e-6)


def test_top_k_keeps_exactly_k():
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(5, 20)).astype(np.float32)
    for k in (1, 3, 7, 20, 25):
        out = _filt(logits, np.ones(5), k * np.ones(5, np.int32),
                    np.ones(5))
        kept = (out > sampling.NEG / 2).sum(axis=1)
        assert (kept == min(k, 20)).all()


def test_top_p_nucleus_and_top1_survives():
    # peaked row: tiny p keeps only the top token; flat row keeps ~p*V
    logits = np.array([[10.0, 0.0, 0.0, 0.0, 0.0],
                       [0.0, 0.0, 0.0, 0.0, 0.0]], np.float32)
    out = _filt(logits, np.ones(2), np.zeros(2, np.int32),
                np.array([0.01, 0.5]))
    assert (out[0] > sampling.NEG / 2).sum() == 1
    # flat: exclusive cumsum < 0.5 keeps ceil(0.5 * 5) = 3 ranks
    assert (out[1] > sampling.NEG / 2).sum() == 3


def test_mixed_batch_rows_are_independent():
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(3, 10)).astype(np.float32)
    mixed = _filt(logits, [0.0, 1.0, 0.5], [0, 4, 0], [1.0, 1.0, 0.7])
    for i, (t, k, p) in enumerate([(0.0, 0, 1.0), (1.0, 4, 1.0),
                                   (0.5, 0, 0.7)]):
        solo = _filt(logits[i:i + 1], [t], [k], [p])
        np.testing.assert_array_equal(mixed[i], solo[0])


# ---------------------------------------------------------------------------
# counter keys: determinism and independence


def test_keys_are_pure_functions_of_seed_and_position():
    seeds = jnp.asarray([7, 7, 9], jnp.uint32)
    pos = jnp.asarray([0, 1, 0], jnp.int32)
    k1 = np.asarray(sampling.keys_for(seeds, pos, sampling.SALT_TOKEN))
    k2 = np.asarray(sampling.keys_for(seeds, pos, sampling.SALT_TOKEN))
    np.testing.assert_array_equal(k1, k2)
    assert not np.array_equal(k1[0], k1[1])     # position matters
    assert not np.array_equal(k1[0], k1[2])     # seed matters
    ka = np.asarray(sampling.keys_for(seeds, pos, sampling.SALT_ACCEPT))
    assert not np.array_equal(k1[0], ka[0])     # salt matters


def test_uniform_for_broadcasts_and_is_deterministic():
    u = np.asarray(sampling.uniform_for(
        jnp.asarray([5, 6], jnp.uint32)[:, None],
        jnp.arange(4)[None, :], sampling.SALT_ACCEPT))
    assert u.shape == (2, 4)
    assert ((0.0 <= u) & (u < 1.0)).all()
    u2 = np.asarray(sampling.uniform_for(
        jnp.asarray([5, 6], jnp.uint32)[:, None],
        jnp.arange(4)[None, :], sampling.SALT_ACCEPT))
    np.testing.assert_array_equal(u, u2)


def test_sampled_stream_matches_distribution_chi_squared():
    """10k Gumbel-max draws from a fixed 8-token distribution must fit
    the softmax probabilities (chi-squared, df=7, alpha=0.001)."""
    logits = jnp.asarray(
        np.array([2.0, 1.5, 1.0, 0.5, 0.0, -0.5, -1.0, -1.5],
                 np.float32))
    n = 10_000
    filt = sampling.filter_logits(
        jnp.broadcast_to(logits, (n, 8)),
        jnp.ones((n,), jnp.float32), jnp.zeros((n,), jnp.int32),
        jnp.ones((n,), jnp.float32))
    toks = np.asarray(sampling.sample_from_filtered(
        filt, jnp.full((n,), 123, jnp.uint32),
        jnp.arange(n, dtype=jnp.int32)))
    expected = n * np.asarray(jax.nn.softmax(logits))
    observed = np.bincount(toks, minlength=8)
    chi2 = float(((observed - expected) ** 2 / expected).sum())
    assert chi2 < 24.32, chi2     # chi2_{0.999, df=7}


def test_accept_prefix_rule_basics():
    """Hand-checkable acceptance: q == p accepts everything; q
    concentrated on a token p excludes rejects at once."""
    v, k = 4, 2
    p = np.full((1, k + 1, v), 0.25, np.float32)
    q_same = np.full((1, k, v), 0.25, np.float32)
    props = np.zeros((1, k), np.int32)
    a, _res = sampling.accept_prefix(
        jnp.asarray(p), jnp.asarray(q_same), jnp.asarray(props),
        jnp.asarray([3], jnp.uint32), jnp.asarray([5], jnp.int32))
    assert int(a[0]) == k         # u * 0.25 <= 0.25 always
    # draft proposes token 0 with certainty but p(0) = 0 -> reject at
    # position 0, resample lands on a token with p > 0
    p0 = np.array([[[0.0, 0.5, 0.5, 0.0]] * (k + 1)], np.float32)
    q0 = np.array([[[1.0, 0.0, 0.0, 0.0]] * k], np.float32)
    a0, res0 = sampling.accept_prefix(
        jnp.asarray(p0), jnp.asarray(q0), jnp.asarray(props),
        jnp.asarray([3], jnp.uint32), jnp.asarray([5], jnp.int32))
    assert int(a0[0]) == 0
    assert int(res0[0]) in (1, 2)


def test_accept_prefix_emitted_marginal_is_target():
    """The speculative exactness proof obligation, empirically: over
    many seeds, the position-0 emitted token (accepted proposal OR
    residual resample) must be distributed as the TARGET p — despite
    proposals coming from a very different draft q. Chi-squared on a
    4-token toy, df=3, alpha=0.001."""
    v, k, n = 4, 1, 10_000
    p_row = np.array([0.5, 0.25, 0.125, 0.125], np.float32)
    q_row = np.array([0.125, 0.125, 0.25, 0.5], np.float32)
    p = np.broadcast_to(p_row, (n, k + 1, v)).astype(np.float32)
    q = np.broadcast_to(q_row, (n, k, v)).astype(np.float32)
    seeds = jnp.arange(n, dtype=jnp.uint32)
    pos0 = jnp.zeros((n,), jnp.int32)
    # proposals drawn from q under the SALT_TOKEN key (as the draft
    # scan would)
    props = sampling.sample_from_filtered(
        jnp.log(jnp.asarray(q[:, 0])), seeds, pos0)[:, None]
    a, res = sampling.accept_prefix(
        jnp.asarray(p), jnp.asarray(q), props, seeds, pos0)
    a = np.asarray(a)
    emitted = np.where(a >= 1, np.asarray(props)[:, 0], np.asarray(res))
    expected = n * p_row
    observed = np.bincount(emitted, minlength=v)
    chi2 = float(((observed - expected) ** 2 / expected).sum())
    assert chi2 < 16.27, chi2     # chi2_{0.999, df=3}
