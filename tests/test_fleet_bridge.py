"""Fleet API -> sharded user-model training (VERDICT r1 item 4).

Trains the zoo BERT through fleet.init + distributed_model +
distributed_optimizer on the 8-device CPU mesh (dp=2 × tp=4) and checks
the losses match a single-device run of the same model step for step —
i.e. GSPMD partitioning with Megatron param placement is semantically
invisible. (reference: fluid/incubate/fleet/collective/__init__.py)"""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, jit
from paddle_tpu.models.bert import Bert, BertConfig, BertForPretraining
import paddle_tpu.parallel.fleet as fleet_mod
from paddle_tpu.parallel.fleet import (Fleet, DistributedStrategy,
                                       megatron_param_spec)


def _bert_and_data(batch=8, seq=32):
    cfg = BertConfig.tiny(hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    pt.seed(123)
    model = BertForPretraining(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype("i4")
    mlm = np.where(rng.rand(batch, seq) < 0.2,
                   rng.randint(0, cfg.vocab_size, (batch, seq)),
                   -1).astype("i4")
    nsp = rng.randint(0, 2, (batch,)).astype("i4")
    return cfg, model, ids, mlm, nsp


def _make_step(model, o):
    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss
    return jit.to_static(step, models=[model], optimizers=[o])


def test_megatron_param_spec_patterns():
    assert megatron_param_spec("encoder.0.attention.qkv.weight",
                               (64, 192)) == P(None, "tp")
    assert megatron_param_spec("encoder.0.attention.qkv.bias",
                               (192,)) == P("tp")
    assert megatron_param_spec("encoder.0.attention.out.weight",
                               (64, 64)) == P("tp", None)
    assert megatron_param_spec("encoder.0.ffn1.weight",
                               (64, 256)) == P(None, "tp")
    assert megatron_param_spec("encoder.0.ffn2.weight",
                               (256, 64)) == P("tp", None)
    assert megatron_param_spec("embeddings.word_embeddings.weight",
                               (1024, 64)) == P()
    assert megatron_param_spec("encoder.0.attn_norm.weight", (64,)) == P()


@pytest.mark.slow
def test_fleet_bert_dp_tp_matches_single_device():
    # ---- single-device reference run -------------------------------
    cfg, model_ref, ids, mlm, nsp = _bert_and_data()
    o_ref = optimizer.SGD(learning_rate=0.1,
                          parameters=model_ref.parameters())
    step_ref = _make_step(model_ref, o_ref)
    ref_losses = [float(step_ref(pt.to_tensor(ids), pt.to_tensor(mlm),
                                 pt.to_tensor(nsp)).numpy())
                  for _ in range(3)]

    # ---- fleet dp×tp run --------------------------------------------
    cfg, model, ids, mlm, nsp = _bert_and_data()  # same seed -> same init
    fleet = Fleet()
    strategy = DistributedStrategy()
    strategy.mesh_shape = {"dp": 2, "tp": 4}
    fleet.init(strategy=strategy)
    model = fleet.distributed_model(model)

    # tp-sharded placement actually happened
    qkv = dict(model.named_parameters())[
        "bert.encoder.0.attention.qkv.weight"]
    assert qkv.data.sharding.spec == P(None, "tp")

    o = fleet.distributed_optimizer(
        optimizer.SGD(learning_rate=0.1, parameters=model.parameters()))
    step = _make_step(model, o)
    tids, tmlm, tnsp = fleet.shard_batch(ids, mlm, nsp)
    losses = [float(step(tids, tmlm, tnsp).numpy()) for _ in range(3)]

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=2e-3)

    # params remain tp-sharded after compiled steps (no silent gather)
    assert qkv.data.sharding.spec == P(None, "tp")


@pytest.mark.slow
def test_fleet_dp_only_matches_single_device():
    cfg, model_ref, ids, mlm, nsp = _bert_and_data(batch=8)
    o_ref = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                               parameters=model_ref.parameters())
    step_ref = _make_step(model_ref, o_ref)
    ref = [float(step_ref(pt.to_tensor(ids), pt.to_tensor(mlm),
                          pt.to_tensor(nsp)).numpy()) for _ in range(2)]

    cfg, model, ids, mlm, nsp = _bert_and_data(batch=8)
    fleet = Fleet()
    fleet.init(mesh_shape={"dp": 8})
    model = fleet.distributed_model(model)
    o = fleet.distributed_optimizer(
        optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                           parameters=model.parameters()))
    step = _make_step(model, o)
    tids, tmlm, tnsp = fleet.shard_batch(ids, mlm, nsp)
    got = [float(step(tids, tmlm, tnsp).numpy()) for _ in range(2)]
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
