"""New dataset modules (wmt14/voc2012/mq2007/image) + real-file parser
coverage via generated fixtures (VERDICT r2 weak #8: the parse paths used
to run only against missing files)."""
import gzip
import os
import struct

import numpy as np
import pytest

from paddle_tpu.dataset import (common, wmt14, voc2012, mq2007, image,
                                mnist, uci_housing)


class TestWmt14:
    def test_reader_contract(self):
        rows = list(wmt14.train(dict_size=200)())
        assert len(rows) == wmt14.TRAIN_N
        src, trg_in, trg_next = rows[0]
        assert trg_in[0] == wmt14.BOS and trg_next[-1] == wmt14.EOS
        assert len(trg_in) == len(trg_next)
        assert max(src) < 200
        # deterministic
        rows2 = list(wmt14.train(dict_size=200)())
        assert rows[0][0] == rows2[0][0]

    def test_get_dict(self):
        sd, td = wmt14.get_dict(50, reverse=False)
        assert sd["<s>"] == 0 and sd["<e>"] == 1 and sd["<unk>"] == 2
        rd, _ = wmt14.get_dict(50)
        assert rd[0] == "<s>"


class TestVoc2012:
    def test_masks_match_images(self):
        rows = list(voc2012.val()())
        assert len(rows) == voc2012.VAL_N
        img, mask = rows[0]
        assert img.shape == (3, voc2012.H, voc2012.W)
        assert mask.shape == (voc2012.H, voc2012.W)
        assert mask.max() < voc2012.CLASSES
        assert mask.dtype == np.uint8


class TestMq2007:
    def test_pointwise_pairwise_listwise(self):
        pts = list(mq2007.train("pointwise")())
        feat, rel = pts[0]
        assert feat.shape == (mq2007.FEATURES,)
        assert rel in (0.0, 1.0, 2.0)

        pairs = list(mq2007.train("pairwise")())
        better, worse = pairs[0]
        assert better.shape == worse.shape == (mq2007.FEATURES,)

        lists = list(mq2007.test("listwise")())
        labels, feats = lists[0]
        assert len(labels) == len(feats) == mq2007.DOCS_PER_QUERY

    def test_real_file_parser(self, tmp_path, monkeypatch):
        d = tmp_path / "mq2007"
        d.mkdir()
        lines = [
            "2 qid:10 1:0.5 2:0.25 46:1.0 #doc1",
            "0 qid:10 1:0.1 2:0.0 #doc2",
            "1 qid:11 3:0.7 #doc3",
        ]
        (d / "train.txt").write_text("\n".join(lines))
        monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
        qs = mq2007._load("train")
        assert len(qs) == 2  # two qids
        rel, feat = qs[0][0]
        assert rel == 2 and feat[0] == pytest.approx(0.5)
        assert feat[45] == pytest.approx(1.0)


class TestImage:
    def test_resize_and_crops(self):
        im = np.arange(40 * 60 * 3, dtype="u1").reshape(40, 60, 3)
        r = image.resize_short(im, 20)
        assert min(r.shape[:2]) == 20 and r.shape[1] == 30
        c = image.center_crop(r, 16)
        assert c.shape[:2] == (16, 16)
        f = image.left_right_flip(c)
        np.testing.assert_array_equal(f[:, 0], c[:, -1])

    def test_simple_transform(self):
        rng = np.random.RandomState(0)
        im = (rng.rand(50, 70, 3) * 255).astype("u1")
        out = image.simple_transform(im, 32, 24, is_train=True, rng=rng,
                                     mean=[1.0, 2.0, 3.0])
        assert out.shape == (3, 24, 24)
        assert out.dtype == np.float32
        out2 = image.simple_transform(im, 32, 24, is_train=False)
        assert out2.shape == (3, 24, 24)


class TestRealFileParsers:
    def test_mnist_idx_parser(self, tmp_path, monkeypatch):
        d = tmp_path / "mnist"
        d.mkdir()
        rng = np.random.RandomState(0)
        imgs = (rng.rand(5, 28, 28) * 255).astype("u1")
        labels = np.arange(5, dtype="u1")
        with gzip.open(d / "train-images-idx3-ubyte.gz", "wb") as f:
            f.write(struct.pack(">IIII", 2051, 5, 28, 28))
            f.write(imgs.tobytes())
        with gzip.open(d / "train-labels-idx1-ubyte.gz", "wb") as f:
            f.write(struct.pack(">II", 2049, 5))
            f.write(labels.tobytes())
        x, y = mnist._parse_idx(str(d / "train-images-idx3-ubyte.gz"),
                                str(d / "train-labels-idx1-ubyte.gz"))
        assert x.shape == (5, 784)
        np.testing.assert_array_equal(y, labels)
        assert x.min() >= -1.0 and x.max() <= 1.0

    def test_uci_housing_file_parser(self, tmp_path, monkeypatch):
        d = tmp_path / "uci_housing"
        d.mkdir()
        rng = np.random.RandomState(1)
        raw = rng.rand(20, 14).astype("f4")
        np.savetxt(d / "housing.data", raw)
        monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
        x, y = uci_housing._load("train")
        assert x.shape == (16, 13) and y.shape == (16, 1)
        xt, yt = uci_housing._load("test")
        assert xt.shape == (4, 13)
