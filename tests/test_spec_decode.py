"""Speculative decoding (PR 17): draft-verify exactness (greedy spec ==
greedy non-spec for ANY draft; self-draft sampled streams are
bit-identical), per-request-seed reproducibility across admission
orders and failover, KV-ledger rollback bookkeeping, zero-recompile
churn with the spec executable family, and the draft-arena budget
arithmetic. All CPU, all fast — the plain/self-draft engines are
module-scoped (one warmup each); counter-keyed streams are history
independent, so sharing a warm engine across tests is sound."""
import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu import serving
from paddle_tpu.serving import kv_cache
from paddle_tpu.serving.generate import GenerateEngine


@pytest.fixture(scope="module")
def model():
    return serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                              max_len=64, seed=1)


@pytest.fixture(scope="module")
def spec_pair():
    return serving.demo_spec_pair(vocab=32, dim=16, heads=2,
                                  draft_layers=1, extra_layers=1,
                                  max_len=64, seed=1, distill=0.2)


def _drive(eng, futs):
    futs = futs if isinstance(futs, list) else [futs]
    for _ in range(3000):
        eng.tick()
        if all(f.done() for f in futs):
            return [f.result() for f in futs]
    raise AssertionError("decode did not finish")


def _engine(model, draft=None, k=4, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page", 16)
    kw.setdefault("max_len", 16)       # single-cap family -> one compile
    kw.setdefault("prompt_buckets", (16,))
    return GenerateEngine(model, start=False, draft_model=draft,
                          spec_k=k, **kw)


@pytest.fixture(scope="module")
def plain_eng(model):
    eng = _engine(model)
    eng.warmup()
    yield eng
    eng.close(drain=False)


@pytest.fixture(scope="module")
def spec_eng(model):
    eng = _engine(model, draft=model, k=4)
    eng.warmup()
    yield eng
    eng.close(drain=False)


# ---------------------------------------------------------------------------
# the KV ledger: note_length / rollback


def test_pool_rollback_is_pure_ledger_truncation():
    pool = kv_cache.KVCachePool({"k0": ((2, 4), "float32")}, slots=2,
                                page=16, max_len=32)
    s = pool.alloc()
    pool.note_length(s, 5)
    assert pool.length(s) == 5
    pool.note_length(s, 10)            # a verify wrote k+1 ahead
    assert pool.rollback(s, 7) == 3    # … and 3 went dead
    assert pool.length(s) == 7
    assert pool.rollback(s, 7) == 0    # no-op rollback drops nothing
    with pytest.raises(ValueError):
        pool.rollback(s, 9)            # growing is note_length's job
    with pytest.raises(ValueError):
        pool.rollback(s, -1)
    with pytest.raises(ValueError):
        pool.note_length(s, 99)        # past capacity
    st = pool.stats()
    assert st["rollbacks"] == 2 and st["rollback_tokens"] == 3
    pool.free(s)
    assert pool.length(s) == 0


def test_bytes_per_token_prices_spec_pair_as_list(spec_pair):
    target, draft = spec_pair
    bt = kv_cache.bytes_per_token(target.kv_spec())
    bd = kv_cache.bytes_per_token(draft.kv_spec())
    assert kv_cache.bytes_per_token(
        [target.kv_spec(), draft.kv_spec()]) == bt + bd
    fits, needed, _ = kv_cache.fits_budget(
        [target.kv_spec(), draft.kv_spec()], slots=4, max_len=64,
        limit_bytes=10 ** 9)
    assert fits and needed == 4 * 64 * (bt + bd)
    n_pair = kv_cache.plan_slots([target.kv_spec(), draft.kv_spec()],
                                 max_len=64, limit_bytes=10 ** 7,
                                 reserve_frac=0.5, max_slots=10 ** 6)
    n_solo = kv_cache.plan_slots(target.kv_spec(), max_len=64,
                                 limit_bytes=10 ** 7, reserve_frac=0.5,
                                 max_slots=10 ** 6)
    # pricing the pair buys fewer slots from the same budget
    assert 1 <= n_pair < n_solo
    assert n_pair == int(0.5 * 10 ** 7 / (64 * (bt + bd)))


# ---------------------------------------------------------------------------
# exactness


def test_greedy_spec_equals_nonspec_any_draft(model, plain_eng):
    """The greedy-parity guarantee: with temperature 0 the accept rule
    keeps a proposal iff it IS the target argmax, and every reject
    resamples from the argmax one-hot — so even a totally unrelated
    draft model yields the target's exact greedy stream."""
    bad_draft = serving.demo_model(vocab=32, dim=16, heads=2, layers=1,
                                   max_len=64, seed=99)
    want = _drive(plain_eng,
                  plain_eng.submit([3, 1, 4, 1, 5],
                                   max_new_tokens=11))[0]
    for k in (1, 4):
        spec = _engine(model, draft=bad_draft, k=k)
        spec.warmup()
        got = _drive(spec, spec.submit([3, 1, 4, 1, 5],
                                       max_new_tokens=11))[0]
        st = spec.stats()
        spec.close(drain=False)
        np.testing.assert_array_equal(got, want)
        assert st["verify_steps"] > 0 and st["spec_proposed"] > 0


def test_sampled_self_draft_is_bit_identical(plain_eng, spec_eng):
    """q == p and shared (seed, position, SALT_TOKEN) keys: the draft
    proposes exactly what non-speculative sampling would draw, and the
    accept test u * q(d) <= p(d) always passes — the streams match bit
    for bit, including top-k/top-p filtered ones."""
    configs = [{"temperature": 1.0},
               {"temperature": 0.8, "top_k": 6},
               {"temperature": 1.2, "top_p": 0.9},
               {"temperature": 1.0, "top_k": 8, "top_p": 0.8}]
    want = [_drive(plain_eng,
                   plain_eng.submit([7, 2], max_new_tokens=12,
                                    sampling=c, seed=100 + i))[0]
            for i, c in enumerate(configs)]
    st0 = spec_eng.stats()
    got = [_drive(spec_eng,
                  spec_eng.submit([7, 2], max_new_tokens=12,
                                  sampling=c, seed=100 + i))[0]
           for i, c in enumerate(configs)]
    st1 = spec_eng.stats()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)
    # self-draft: everything the draft proposed was accepted
    assert (st1["spec_accepted"] - st0["spec_accepted"]
            == st1["spec_proposed"] - st0["spec_proposed"] > 0)


def test_eos_mid_chunk_truncates_spec_stream(plain_eng, spec_eng):
    """An EOS inside the accepted prefix must terminate the sequence AT
    the EOS — tokens past it are never emitted, exactly like the
    non-speculative path."""
    probe = _drive(plain_eng,
                   plain_eng.submit([5, 9], max_new_tokens=12,
                                    sampling={"temperature": 1.3},
                                    seed=7))[0]
    eos = int(probe[len(probe) // 2])      # a token we KNOW occurs
    want = _drive(plain_eng,
                  plain_eng.submit([5, 9], max_new_tokens=12,
                                   eos_token=eos,
                                   sampling={"temperature": 1.3},
                                   seed=7))[0]
    assert want[-1] == eos
    got = _drive(spec_eng,
                 spec_eng.submit([5, 9], max_new_tokens=12,
                                 eos_token=eos,
                                 sampling={"temperature": 1.3},
                                 seed=7))[0]
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# seed reproducibility across admission orders


@pytest.mark.parametrize("speculative", [False, True])
def test_seed_reproducible_across_admission_orders(
        model, plain_eng, spec_eng, speculative):
    """The same (prompt, params, seed) must produce the same stream no
    matter when it was admitted, who it shared the batch with, or
    whether speculation was on — the counter-key contract."""
    draft = model if speculative else None
    eng = spec_eng if speculative else plain_eng
    reqs = [([2 + i, 5], {"temperature": 1.0, "top_k": 8}, 40 + i)
            for i in range(4)]
    futs = [eng.submit(p, max_new_tokens=12, sampling=c, seed=s)
            for p, c, s in reqs]
    batch_all = _drive(eng, futs)
    # admit one at a time, in reverse, with decode ticks in between
    eng2 = _engine(model, draft=draft)
    eng2.warmup()
    staggered = {}
    for p, c, s in reversed(reqs):
        f = eng2.submit(p, max_new_tokens=12, sampling=c, seed=s)
        eng2.tick()                      # partial progress before the
        staggered[s] = f                 # next admission
    for (p, c, s), want in zip(reqs, batch_all):
        got = _drive(eng2, staggered[s])[0]
        np.testing.assert_array_equal(got, want)
    eng2.close(drain=False)


@pytest.mark.parametrize("speculative", [False, True])
def test_failover_requeue_is_bit_identical(
        model, plain_eng, spec_eng, speculative):
    """Satellite 1: hang a replica mid-generation, disown its in-flight
    sequences, requeue on a second engine — the adopting replica's
    re-prefill must regenerate the exact stream a clean run produces,
    sampled or speculative (the docstring's claim, enforced)."""
    draft = model if speculative else None
    a = _engine(model, draft=draft)
    a.warmup()
    fut = a.submit([11, 3, 8], max_new_tokens=12,
                   sampling={"temperature": 0.9, "top_p": 0.95},
                   seed=77)
    for _ in range(2):
        a.tick()                 # partial output exists on replica A
    assert not fut.done()
    moved = a.disown_inflight() + a.steal_pending()
    assert len(moved) == 1
    a.close(drain=False)

    b = spec_eng if speculative else plain_eng
    b.requeue(moved)
    got = _drive(b, fut)[0]
    # the clean reference: same request, fresh admission, no failover —
    # per-request counter keys make it independent of the slot history
    want = _drive(b, b.submit([11, 3, 8], max_new_tokens=12,
                              sampling={"temperature": 0.9,
                                        "top_p": 0.95},
                              seed=77))[0]
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# zero-recompile churn + bookkeeping


def test_spec_churn_mints_no_executables(spec_eng):
    base = spec_eng.executables()
    st0 = spec_eng.stats()
    rng = np.random.default_rng(0)
    futs = []
    for i in range(10):
        samp = (None if i % 3 == 0
                else {"temperature": 0.5 + 0.1 * i,
                      "top_k": int(i % 5), "top_p": 0.8 + 0.02 * i})
        futs.append(spec_eng.submit(rng.integers(0, 32, size=1 + i % 7),
                                    max_new_tokens=4 + i % 5,
                                    sampling=samp, seed=i))
    _drive(spec_eng, futs)
    assert spec_eng.executables() == base
    st1 = spec_eng.stats()
    assert st1["completed"] - st0["completed"] == 10
    assert st1["spec_accepted"] <= st1["spec_proposed"]
    # verify over-writes settle via ledger rollback every tick
    assert st1["pool_rollbacks"] > st0["pool_rollbacks"]


def test_draft_pool_tracks_target_capacity_to_the_brim(model):
    """Growth keeps the draft arena in lockstep with the target, AND a
    request admitted at exactly prompt + max_new == max_len survives
    speculation: near the budget the verify chunk reaches past max_len
    — the device drops the out-of-range writes and the ledger clamps,
    so the stream completes and still matches the non-spec one
    (regression: this used to raise out of _ensure_capacity)."""
    eng = _engine(model, max_len=32)
    eng.warmup()
    want = _drive(eng, eng.submit(list(range(1, 9)),
                                  max_new_tokens=24))[0]
    eng.close(drain=False)
    spec = _engine(model, draft=model, k=4, max_len=32)
    spec.warmup()
    assert spec.draft_pool.capacity == spec.pool.capacity == 16
    f = spec.submit(list(range(1, 9)), max_new_tokens=24)  # 8+24 == 32
    got = _drive(spec, f)[0]
    assert spec.pool.capacity == 32          # the sequence outgrew page
    assert spec.draft_pool.capacity == spec.pool.capacity
    base = spec.executables()
    spec.close(drain=False)
    assert base == spec.executables()        # growth minted nothing
    assert len(got) == 24                    # full budget, no early stop
    np.testing.assert_array_equal(got, want)


def test_spec_validates_vocab_k_and_verify_fn(model):
    other_vocab = serving.demo_model(vocab=16, dim=16, heads=2,
                                     layers=1, max_len=64, seed=2)
    with pytest.raises(ValueError, match="vocab"):
        _engine(model, draft=other_vocab)
    with pytest.raises(ValueError, match="spec_k"):
        _engine(model, draft=model, k=0)

    class _Shim:
        """model surface minus verify_fn."""
        def __init__(self, m):
            self._m = m
            self.vocab = m.vocab
            self.state = m.state
            self.device = None
            self.kv_spec = m.kv_spec
            self.prefill_fn = m.prefill_fn
            self.decode_fn = m.decode_fn

    with pytest.raises(ValueError, match="verify_fn"):
        _engine(_Shim(model), draft=model)


# ---------------------------------------------------------------------------
# ISSUE 18: preemption landing inside a speculative chunk


def test_preempt_between_draft_and_verify_requeues_bit_identical(
        model, spec_eng):
    """A preemption drain lands at the worst possible instant: after
    the draft scan proposed a chunk but before the target verified it.
    The disown must reclaim the slot and both KV ledgers cleanly (the
    settle loop skips disowned slots; the in-flight verify's writes
    die with the ledger), and the requeued request must regenerate a
    bit-identical stream on the adopting replica."""
    a = _engine(model, draft=model)
    a.warmup()
    fut = a.submit([9, 4, 17, 2], max_new_tokens=12,
                   sampling={"temperature": 0.9, "top_p": 0.95},
                   seed=88)
    a.tick()                           # prefill seats the sequence
    assert not fut.done() and a.pool.used_slots() == 1

    orig = a._get_verify
    moved = []

    def hijack(cap):
        real = orig(cap)

        def wrapper(*args, **kw):
            if not moved:              # the notice arrives mid-chunk
                moved.extend(a.disown_inflight())
            return real(*args, **kw)   # verify runs against a dead slot
        return wrapper

    a._get_verify = hijack
    for _ in range(4):
        a.tick()
        if moved:
            break
    assert len(moved) == 1 and not fut.done()
    # ledger rollback: slot freed, both arenas read empty for it
    assert a.pool.used_slots() == 0
    assert all(s.req is None for s in a._slots)
    assert a.pool.length(0) == 0 and a.draft_pool.length(0) == 0
    # the engine survived verifying into the disowned slot
    a.tick()
    a.close(drain=False)

    spec_eng.requeue(moved)
    got = _drive(spec_eng, fut)[0]
    want = _drive(spec_eng, spec_eng.submit(
        [9, 4, 17, 2], max_new_tokens=12,
        sampling={"temperature": 0.9, "top_p": 0.95}, seed=88))[0]
    np.testing.assert_array_equal(got, want)
