"""Tests for paddle_tpu.reader decorators and paddle_tpu.dataset loaders
(reference: python/paddle/reader/tests/decorator_test.py and
dataset/tests/)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import reader as R
from paddle_tpu import dataset


def _counter(n):
    def creator():
        yield from range(n)
    return creator


def test_reader_decorators_compose():
    r = R.firstn(_counter(100), 10)
    assert list(r()) == list(range(10))

    r = R.map_readers(lambda a, b: a + b, _counter(5), _counter(5))
    assert list(r()) == [0, 2, 4, 6, 8]

    r = R.chain(_counter(3), _counter(2))
    assert list(r()) == [0, 1, 2, 0, 1]

    r = R.compose(_counter(3), R.map_readers(lambda x: (x, x * 2),
                                             _counter(3)))
    assert list(r()) == [(0, 0, 0), (1, 1, 2), (2, 2, 4)]

    with pytest.raises(ValueError):
        list(R.compose(_counter(3), _counter(4))())

    r = R.shuffle(_counter(20), buf_size=8)
    got = list(r())
    assert sorted(got) == list(range(20))

    r = R.buffered(_counter(50), size=8)
    assert list(r()) == list(range(50))

    r = R.cache(_counter(5))
    assert list(r()) == list(r()) == [0, 1, 2, 3, 4]

    r = R.batch(_counter(7), batch_size=3)
    bs = list(r())
    assert bs == [[0, 1, 2], [3, 4, 5], [6]]
    r = R.batch(_counter(7), batch_size=3, drop_last=True)
    assert list(r()) == [[0, 1, 2], [3, 4, 5]]


def test_xmap_and_multiprocess_readers():
    r = R.xmap_readers(lambda x: x * 10, _counter(30), 4, 8, order=True)
    assert list(r()) == [i * 10 for i in range(30)]
    r = R.xmap_readers(lambda x: x * 10, _counter(30), 4, 8, order=False)
    assert sorted(list(r())) == [i * 10 for i in range(30)]
    r = R.multiprocess_reader([_counter(10), _counter(10)])
    assert sorted(list(r())) == sorted(list(range(10)) * 2)


def test_mnist_format_and_determinism():
    imgs, labels = dataset.mnist.train_arrays()
    assert imgs.shape[1] == 784 and imgs.dtype == np.float32
    assert imgs.min() >= -1.0 and imgs.max() <= 1.0
    assert set(np.unique(labels)).issubset(set(range(10)))
    imgs2, labels2 = dataset.mnist.train_arrays()
    np.testing.assert_array_equal(imgs, imgs2)  # deterministic

    sample = next(dataset.mnist.train()())
    assert sample[0].shape == (784,) and isinstance(sample[1], int)


def test_cifar_imdb_imikolov_formats():
    img, lab = next(dataset.cifar.train10()())
    assert img.shape == (3072,) and 0 <= lab < 10
    img, lab = next(dataset.cifar.train100()())
    assert 0 <= lab < 100

    ids, lab = next(dataset.imdb.train()())
    assert lab in (0, 1) and all(0 <= i < dataset.imdb.VOCAB for i in ids)

    gram = next(dataset.imikolov.train(n=5)())
    assert len(gram) == 5

    src, trg_in, trg_next = next(dataset.wmt16.train()())
    assert trg_in[0] == dataset.wmt16.BOS
    assert trg_next[-1] == dataset.wmt16.EOS
    assert len(trg_in) == len(trg_next)

    x, y = next(dataset.uci_housing.train()())
    assert x.shape == (13,)

    u, g, age, job, m, cats, title, rating = next(
        dataset.movielens.train()())
    assert 1.0 <= rating <= 5.0

    words, pred, labels = next(dataset.conll05.test()())
    assert len(words) == len(labels)


def test_mnist_pipeline_trains_lenet():
    """End-to-end: dataset -> reader decorators -> batch -> train. The
    synthetic MNIST must be learnable (accuracy well above chance)."""
    from paddle_tpu import nn, optimizer
    import paddle_tpu.nn.functional as F

    pt.seed(0)
    train_reader = R.batch(
        R.shuffle(R.firstn(dataset.mnist.train(), 2000), buf_size=512),
        batch_size=128)

    model = nn.Sequential(nn.Linear(784, 64), nn.ReLU(),
                          nn.Linear(64, 10))
    o = optimizer.Adam(learning_rate=3e-3, parameters=model.parameters())
    for epoch in range(4):
        for batch in train_reader():
            x = np.stack([s[0] for s in batch])
            y = np.array([s[1] for s in batch], "i4")
            loss = F.cross_entropy(model(pt.to_tensor(x)), pt.to_tensor(y))
            loss.backward()
            o.step()
            o.clear_grad()

    imgs, labels = dataset.mnist.test_arrays()
    logits = model(pt.to_tensor(imgs[:500])).numpy()
    acc = (logits.argmax(-1) == labels[:500]).mean()
    assert acc > 0.7, acc
