"""paddle_tpu.monitor.trace / monitor.xla — span tracer semantics,
Chrome-trace export, the flight recorder, XLA-measured cost capture,
measured-MFU reporting, and the zero-cost-when-disabled contract."""
import json
import os
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import trace, xla


@pytest.fixture(autouse=True)
def _clean():
    """Tracer + monitor are process-global: every test starts disabled
    and empty, and leaves nothing behind."""
    monitor.disable(flush_counters=False)
    monitor.reset()
    trace.disable()
    trace.clear()
    yield
    monitor.disable(flush_counters=False)
    monitor.reset()
    trace.disable()
    trace.clear()


# -- disabled-mode contract ---------------------------------------------------

def test_disabled_span_is_shared_null_and_records_nothing():
    # ONE flag check, one shared object — no allocation per call site
    assert trace.span("a") is trace._NULL
    assert trace.span("b", k=1) is trace._NULL
    with trace.span("x"):
        pass
    trace.instant("marker")
    trace.complete("op", 0.0, 1.0)

    @trace.traced
    def f():
        return 42

    assert f() == 42
    assert trace.events() == []
    assert not trace.enabled()


# -- recording ----------------------------------------------------------------

def test_span_records_nested_begin_end_pairs():
    trace.enable()
    with trace.span("outer", step=1):
        with trace.span("inner"):
            pass
    evs = trace.events()
    assert [(e[0], e[1]) for e in evs] == [
        ("B", "outer"), ("B", "inner"), ("E", "inner"), ("E", "outer")]
    assert evs[0][4] == {"step": 1}     # args ride the begin event
    # timestamps are monotone non-decreasing within the thread
    ts = [e[3] for e in evs]
    assert ts == sorted(ts)


def test_complete_and_instant_events():
    trace.enable()
    t0 = time.perf_counter()
    trace.complete("dispatch.add", t0, t0 + 1e-3, n=2)
    trace.instant("collective.c_allreduce_sum", axis="dp")
    kinds = [e[0] for e in trace.events()]
    assert kinds == ["X", "I"]
    x = trace.events()[0]
    assert x[1] == "dispatch.add" and x[4] == pytest.approx(1e-3)


def test_traced_decorator_bare_and_named():
    trace.enable()

    @trace.traced
    def plain():
        return 1

    @trace.traced("custom.label")
    def named():
        return 2

    assert plain() == 1 and named() == 2
    names = [e[1] for e in trace.events() if e[0] == "B"]
    assert any("plain" in n for n in names)
    assert "custom.label" in names


def test_ring_buffer_is_bounded():
    trace.enable(buffer_size=8)
    try:
        for i in range(20):
            trace.instant(f"m{i}")
        evs = trace.events()
        assert len(evs) == 8
        assert evs[-1][1] == "m19"      # oldest fell off, newest kept
        assert trace.events(last=3)[0][1] == "m17"
    finally:
        trace.enable(buffer_size=trace.DEFAULT_BUFFER)


def test_disable_keeps_buffer_clear_empties_it():
    trace.enable()
    trace.instant("kept")
    trace.disable()
    assert [e[1] for e in trace.events()] == ["kept"]
    trace.clear()
    assert trace.events() == []


def test_bridge_annotation_smoke():
    # TraceAnnotation bridging must never break span recording
    trace.enable(bridge=True)
    with trace.span("bridged"):
        pass
    assert [e[0] for e in trace.events()] == ["B", "E"]


# -- export -------------------------------------------------------------------

def test_export_chrome_trace_thread_tracks(tmp_path):
    trace.enable()

    def worker():
        with trace.span("producer.work"):
            time.sleep(0.005)

    t = threading.Thread(target=worker, name="producer-thread")
    with trace.span("main.loop"):
        t.start()
        t.join()

    doc = trace.export_chrome_trace()
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    tnames = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert "producer-thread" in tnames
    real = [e for e in evs if e["ph"] != "M"]
    assert len({e["tid"] for e in real}) >= 2    # two tracks
    for e in real:                               # loadable trace-event JSON
        assert {"ph", "pid", "tid", "name", "ts"} <= set(e)

    # a directory gets trace-<pid>.json; explicit *.json paths verbatim
    p = trace.export_chrome_trace(str(tmp_path))
    assert p == os.path.join(str(tmp_path), f"trace-{os.getpid()}.json")
    with open(p, encoding="utf-8") as fh:
        assert json.load(fh)["traceEvents"]
    p2 = trace.export_chrome_trace(str(tmp_path / "custom.json"))
    assert p2.endswith("custom.json") and os.path.exists(p2)


def test_dispatch_timer_feeds_complete_events(tmp_path):
    monitor.enable(str(tmp_path), time_dispatch=True)
    trace.enable()
    (pt.to_tensor(np.ones(4, "f4")) + 1).numpy()
    names = [e[1] for e in trace.events() if e[0] == "X"]
    assert any(n.startswith("dispatch.") for n in names)


def test_monitor_enable_env_turns_trace_on(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    monitor.enable(str(tmp_path))
    assert trace.enabled()


# -- flight recorder ----------------------------------------------------------

def test_flight_record_contents(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    path = monitor.enable(str(tmp_path))
    trace.enable()
    monitor.counter("unit.counter").inc(3)
    with trace.span("hung.phase"):
        d = trace.flight_record("unit_test", step=7, extra={"k": "v"})
    assert d and os.path.isdir(d)

    with open(os.path.join(d, "meta.json"), encoding="utf-8") as fh:
        meta = json.load(fh)
    assert meta["reason"] == "unit_test" and meta["step"] == 7
    assert meta["extra"] == {"k": "v"}

    with open(os.path.join(d, "counters.json"), encoding="utf-8") as fh:
        counters = json.load(fh)
    assert counters["unit.counter"] == 3

    with open(os.path.join(d, "trace.json"), encoding="utf-8") as fh:
        tr = json.load(fh)
    begins = [e["name"] for e in tr["traceEvents"] if e["ph"] == "B"]
    assert "hung.phase" in begins
    # the in-flight span is UNCLOSED in the dump — that's the evidence
    # of which phase was running when the recorder fired
    assert not any(e["ph"] == "E" and e["name"] == "hung.phase"
                   for e in tr["traceEvents"])

    recs = [r for r in monitor.read_jsonl(path)
            if r.get("kind") == "flight_record"]
    assert recs and recs[0]["path"] == d


def test_flight_record_includes_hlo_of_captured_executable(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    monitor.enable(str(tmp_path))
    trace.enable()
    fn = jax.jit(lambda x: x * 2.0)
    xla.aot_capture(fn, "unit.hlo", (jnp.ones((4,), jnp.float32),))
    d = trace.flight_record("with_hlo")
    assert d is not None
    hlo_files = [f for f in os.listdir(d) if f.startswith("hlo-")]
    assert hlo_files, os.listdir(d)
    with open(os.path.join(d, hlo_files[0]), encoding="utf-8") as fh:
        assert "HloModule" in fh.read()


def test_flight_record_rate_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_MAX", "2")
    trace.enable()
    assert trace.flight_record("capped") is not None
    assert trace.flight_record("capped") is not None
    assert trace.flight_record("capped") is None    # budget spent


def test_flight_record_never_raises(tmp_path, monkeypatch):
    # an unwritable base dir must yield None, not a second crash
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR",
                       os.path.join(str(tmp_path), "file-not-dir", "x"))
    with open(os.path.join(str(tmp_path), "file-not-dir"), "w") as fh:
        fh.write("block")
    trace.enable()
    assert trace.flight_record("doomed") is None


def test_watchdog_stall_writes_flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    from paddle_tpu.resilience.watchdog import Watchdog
    path = monitor.enable(str(tmp_path))
    trace.enable()
    wd = Watchdog(min_deadline=0.05, poll=0.01).start()
    try:
        with wd.step(3):
            with trace.span("stuck.phase"):
                time.sleep(0.4)
    finally:
        wd.stop()
    dumps = [r for r in monitor.read_jsonl(path)
             if r.get("kind") == "watchdog_dump"]
    assert dumps and dumps[0]["flight_dir"]
    assert os.path.isdir(dumps[0]["flight_dir"])


def test_fit_crash_writes_flight_record(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    from paddle_tpu import hapi, io, nn, optimizer as opt
    path = monitor.enable(str(tmp_path))
    trace.enable()
    rng = np.random.RandomState(0)
    ds = io.TensorDataset(rng.randn(32, 4).astype("f4"),
                          rng.randint(0, 2, (32,)).astype("i4"))
    m = hapi.Model(nn.Sequential(nn.Linear(4, 2)))

    def boom(outs, labels):
        raise RuntimeError("boom")

    m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                parameters=m.parameters()),
              loss_function=boom)
    with pytest.raises(RuntimeError, match="boom"):
        m.fit(ds, batch_size=8, epochs=1, verbose=0, shuffle=False)
    recs = [r for r in monitor.read_jsonl(path)
            if r.get("kind") == "flight_record"]
    assert any(r["reason"] == "fit_crash" for r in recs)


# -- monitor.xla --------------------------------------------------------------

class _FakeMem:
    argument_size_in_bytes = 100.0
    output_size_in_bytes = 50.0
    temp_size_in_bytes = 30.0
    alias_size_in_bytes = 20.0
    generated_code_size_in_bytes = 10.0


class _FakeCompiled:
    def cost_analysis(self):
        return [{"flops": 1e9, "bytes accessed": 2e6,
                 "transcendentals": 5.0}]

    def memory_analysis(self):
        return _FakeMem()

    def as_text(self):
        return "HloModule fake"


def test_xla_capture_and_accessors(tmp_path):
    path = monitor.enable(str(tmp_path))
    info = xla.capture("fake", _FakeCompiled())
    assert info["flops"] == 1e9
    assert info["bytes_accessed"] == 2e6
    assert info["peak_memory"] == 100 + 50 + 30 - 20
    assert xla.flops("fake") == 1e9
    assert xla.flops() == 1e9                   # None label -> newest
    assert xla.bytes_accessed() == 2e6
    assert xla.peak_memory() == 160.0
    assert xla.labels() == ["fake"]
    assert xla.last()[0] == "fake"
    assert "HloModule" in xla.hlo_text()
    assert monitor.registry().value("xla.flops.fake") == 1e9
    recs = [r for r in monitor.read_jsonl(path)
            if r.get("kind") == "xla_cost"]
    assert recs and recs[0]["label"] == "fake"
    assert xla.measured_mfu(1.0, peak_flops=1e10) == pytest.approx(0.1)


def test_xla_eviction_keeps_newest():
    for i in range(xla.MAX_ENTRIES + 5):
        xla.capture(f"e{i}", _FakeCompiled())
    labels = xla.labels()
    assert len(labels) == xla.MAX_ENTRIES
    assert labels[-1] == f"e{xla.MAX_ENTRIES + 4}"
    assert "e0" not in labels


def test_aot_capture_real_jit_and_fallback():
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    args = (jnp.ones((8,), jnp.float32),)
    compiled = xla.aot_capture(fn, "unit.jit", args)
    assert hasattr(compiled, "cost_analysis")   # swapped for Compiled
    np.testing.assert_allclose(np.asarray(compiled(*args)),
                               np.full((8,), 3.0, "f4"))
    assert xla.get("unit.jit") is not None
    # an already-compiled object is captured in place
    assert xla.aot_capture(compiled, "unit.jit2", args) is compiled
    assert "unit.jit2" in xla.labels()
    # any failure returns the original callable untouched
    sentinel = object()
    assert xla.aot_capture(sentinel, "nope", args) is sentinel
    assert "nope" not in xla.labels()


def test_executor_captures_cost_on_cache_miss(tmp_path):
    monitor.enable(str(tmp_path))
    pt.enable_static()
    try:
        from paddle_tpu import static
        from paddle_tpu.fluid import layers as FL
        prog, sprog = static.Program(), static.Program()
        with static.program_guard(prog, sprog):
            x = static.data("x", [4, 8], "float32")
            y = FL.fc(x, 2)
        exe = static.Executor()
        exe.run(sprog)
        exe.run(prog, feed={"x": np.ones((4, 8), "f4")}, fetch_list=[y])
        labels = xla.labels()
        assert any(lb.startswith("exec.p") for lb in labels)
    finally:
        pt.disable_static()


def test_to_static_captures_cost_on_compile(tmp_path):
    monitor.enable(str(tmp_path))
    from paddle_tpu import jit as pjit

    def double(x):
        return x * 2

    fn = pjit.to_static(double)
    fn(pt.to_tensor(np.ones(4, "f4"))).numpy()
    assert "jit.double" in xla.labels()


# -- StepMonitor measured MFU -------------------------------------------------

def test_step_monitor_reports_measured_mfu_and_flags_divergence(tmp_path):
    monitor.enable(str(tmp_path))
    sm = monitor.StepMonitor(items_per_step=4, flops_per_step=1e6,
                             peak_flops=1e12, label="t",
                             measured_flops_per_step=2e6)
    sm.start()
    time.sleep(0.002)
    with pytest.warns(UserWarning, match="diverges"):
        rec = sm.step()
    assert rec["mfu_measured"] is not None
    assert rec["flops_measured_ratio"] == pytest.approx(2.0)
    assert monitor.registry().value("xla.mfu_divergence") == 1
    time.sleep(0.002)
    rec2 = sm.step()                    # warns ONCE, keeps flagging
    assert rec2["flops_measured_ratio"] == pytest.approx(2.0)
    s = sm.summary()
    assert s["mfu_measured"] is not None
    assert s["flops_per_step_measured"] == 2e6
    assert monitor.registry().value(
        "step.t.mfu_measured") == pytest.approx(rec2["mfu_measured"],
                                                rel=0.5)


def test_step_monitor_pulls_flops_from_xla_capture(tmp_path):
    monitor.enable(str(tmp_path))
    xla.capture("stepexe", _FakeCompiled())     # 1e9 flops
    sm = monitor.StepMonitor(flops_per_step=1e9, peak_flops=1e12,
                             label="x", xla_label="stepexe")
    sm.start()
    time.sleep(0.002)
    rec = sm.step()
    assert rec.get("mfu_measured") is not None
    # identical analytic/measured counts -> no divergence flag
    assert "flops_measured_ratio" not in rec


def test_step_monitor_no_measured_without_capture(tmp_path):
    monitor.enable(str(tmp_path))
    sm = monitor.StepMonitor(flops_per_step=1e6, peak_flops=1e12,
                             label="bare")
    sm.start()
    rec = sm.step()
    assert "mfu_measured" not in rec
