"""Composed multi-axis training at BERT-base GEOMETRY through the
user-facing fleet API (VERDICT r3 #3).

Two mesh layouts on the 8-device CPU mesh:
  dp2 x pp2 x tp2 — 12x768 BERT (scaled seq/vocab), PipelineStack trunk,
    AdamW, dropout ON (exercises the RNG carry through the pp scan),
    flash-capable attention (XLA fallback off-TPU);
  dp2 x sp2 x ep2 — same geometry with MoE FFN layers sharded over ep
    and tokens sharded over (dp, sp).

reference: fleet collective DistributedStrategy + PipelineOptimizer
(python/paddle/fluid/incubate/fleet/collective/__init__.py,
fluid/optimizer.py)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, jit
from paddle_tpu.models.bert import BertConfig, BertForPretraining
from paddle_tpu.parallel.fleet import Fleet, DistributedStrategy

BATCH, SEQ, VOCAB = 8, 64, 4096


def _base_cfg(**kw):
    # BERT-base geometry: 12 layers x 768 hidden x 12 heads x 3072 ffn.
    # seq/vocab scaled (the geometry is what stresses the shardings).
    d = dict(vocab_size=VOCAB, num_hidden_layers=12, hidden_size=768,
             num_attention_heads=12, intermediate_size=3072,
             max_position_embeddings=SEQ, use_recompute=True,
             use_flash_attention=True)
    d.update(kw)
    return BertConfig.base(**d)


def _data(rng_seed=0, batch=BATCH, seq=SEQ, vocab=VOCAB):
    rng = np.random.RandomState(rng_seed)
    ids = rng.randint(0, vocab, (batch, seq)).astype("i4")
    mlm = np.where(rng.rand(batch, seq) < 0.15,
                   rng.randint(0, vocab, (batch, seq)), -1).astype("i4")
    nsp = rng.randint(0, 2, (batch,)).astype("i4")
    return ids, mlm, nsp


def _train(model, fleet, steps, shard_tokens_over_sp=False,
           add_moe_aux=False):
    """Train `steps` on ONE batch; return (eval_before, train_losses,
    eval_after) — the eval losses are dropout-free, so fitting the batch
    must strictly reduce them (robust against dropout/Adam noise)."""
    # post-LN BERT at 12 layers diverges without warmup above ~1e-4;
    # 1e-5 memorizes the single batch monotonically
    o = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-5,
                        parameters=model.parameters()))

    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp)
        if add_moe_aux:
            loss = loss + nn.moe_aux_loss(model)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def eval_loss(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        return model.loss(logits, nsp_logits, mlm, nsp)

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    ceval = jit.to_static(eval_loss, models=[model], optimizers=[])
    ids, mlm, nsp = _data()
    if shard_tokens_over_sp:
        mesh = fleet.mesh
        tok = NamedSharding(mesh, P("dp", "sp"))
        row = NamedSharding(mesh, P("dp"))
        t = (pt.to_tensor(jax.device_put(ids, tok)),
             pt.to_tensor(jax.device_put(mlm, tok)),
             pt.to_tensor(jax.device_put(nsp, row)))
    else:
        t = fleet.shard_batch(pt.to_tensor(ids), pt.to_tensor(mlm),
                              pt.to_tensor(nsp))
    model.eval()
    before = float(ceval(*t).numpy())
    model.train()
    train_losses = [float(cstep(*t).numpy()) for _ in range(steps)]
    model.eval()
    after = float(ceval(*t).numpy())
    model.train()
    return before, train_losses, after


def test_composed_bert_base_dp_pp_tp_adamw_recompute():
    cfg = _base_cfg()
    pt.seed(7)
    model = BertForPretraining(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    assert n_params > 80e6  # genuinely base-sized trunk

    fleet = Fleet()
    st = DistributedStrategy()
    st.mesh_shape = {"dp": 2, "pp": 2, "tp": 2}
    st.recompute = True  # per-stage jax.checkpoint inside the pp scan
    fleet.init(strategy=st)
    model.bert.encoder = fleet.pipeline_stack(list(model.bert.encoder))
    assert model.bert.encoder._remat
    model = fleet.distributed_model(model)

    # trunk params stacked over pp AND column/row split over tp
    stk = model.bert.encoder
    qkv = stk._parameters["stk_attention__qkv__weight"]
    assert qkv.data.sharding.spec[0] == "pp"
    assert "tp" in jax.tree_util.tree_leaves(tuple(qkv.data.sharding.spec))

    before, losses, after = _train(model, fleet, steps=3)
    assert np.isfinite(losses).all(), losses
    assert after < before, (before, losses, after)


def test_composed_bert_base_dp_sp_ep_moe():
    cfg = _base_cfg(moe_num_experts=4, moe_every=3)
    pt.seed(7)
    model = BertForPretraining(cfg)
    assert any(l.moe is not None for l in model.bert.encoder)

    fleet = Fleet()
    st = DistributedStrategy()
    st.mesh_shape = {"dp": 2, "sp": 2, "ep": 2}
    fleet.init(strategy=st)
    model = fleet.distributed_model(model)

    # expert-stacked weights live on the ep axis
    moe_layer = next(l for l in model.bert.encoder if l.moe is not None)
    assert moe_layer.moe.experts_w1.data.sharding.spec[0] == "ep"

    before, losses, after = _train(model, fleet, steps=3,
                                   shard_tokens_over_sp=True,
                                   add_moe_aux=True)
    assert np.isfinite(losses).all(), losses
    assert after < before, (before, losses, after)


def test_composed_model_checkpoint_roundtrip(tmp_path):
    """fleet.save_persistables / load_persistables on the COMPOSED model
    (pp-stacked trunk + MoE + optimizer slots): bit-exact restore with
    placements preserved (tiny scale; the geometry tests above cover
    scale)."""
    cfg = BertConfig.tiny(use_recompute=True, moe_num_experts=2,
                          moe_every=1, hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    pt.seed(11)
    model = BertForPretraining(cfg)
    fleet = Fleet()
    st = DistributedStrategy()
    st.mesh_shape = {"dp": 2, "pp": 2, "tp": 2}
    st.recompute = True
    fleet.init(strategy=st)
    model.bert.encoder = fleet.pipeline_stack(list(model.bert.encoder))
    model = fleet.distributed_model(model)
    o = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-4,
                        parameters=model.parameters()))

    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp) + \
            nn.moe_aux_loss(model)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    ids, mlm, nsp = _data(batch=8, seq=32, vocab=cfg.vocab_size)
    t = fleet.shard_batch(pt.to_tensor(ids), pt.to_tensor(mlm),
                          pt.to_tensor(nsp))
    cstep(*t)

    ckpt = str(tmp_path / "composed_ckpt")
    fleet.save_persistables(dirname=ckpt, model=model, optimizer=o)
    before = {k: np.asarray(jax.device_get(v.data))
              for k, v in model.state_dict().items()}
    o_before = {k: np.asarray(jax.device_get(v.data))
                for k, v in _flat_opt_state(o).items()}
    loss_ref = float(cstep(*t).numpy())  # the step a resume must replay

    # clobber params AND optimizer slots, restore, compare bit-exact
    for p in model.parameters():
        p.data = p.data * 0.0
    for v in _flat_opt_state(o).values():
        v.data = v.data * 0.0
    fleet.load_persistables(dirname=ckpt, model=model, optimizer=o)
    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(
            before[k], np.asarray(jax.device_get(v.data)), err_msg=k)
    for k, v in _flat_opt_state(o).items():
        np.testing.assert_array_equal(
            o_before[k], np.asarray(jax.device_get(v.data)), err_msg=k)
    stk = model.bert.encoder
    some = stk._parameters[stk._flat_names[0]]
    assert some.data.sharding.spec[0] == "pp"
    # dropout is off: the resumed step replays the reference step exactly
    loss_resumed = float(cstep(*t).numpy())
    np.testing.assert_allclose(loss_resumed, loss_ref, rtol=1e-6)


def _flat_opt_state(o):
    """name -> slot Tensor map for a (Distributed)Optimizer."""
    out = {}
    for pid, slots in o._accumulators.items():
        for sname, t in slots.items():
            out[f"{pid}.{sname}"] = t
    return out


def test_composed_ctr_sharded_embedding_dp_mp():
    """PS/CTR redesign at scale under the composed fleet stack
    (VERDICT r4 task 6): WideDeep AND DeepFM with 100k-row embedding
    tables row-sharded over mp (dp2 x mp2), AdamW; eval loss on the
    memorized batch must drop and the tables must actually carry
    P('mp', None). reference: fluid/incubate/fleet/parameter_server/
    distribute_transpiler/__init__.py."""
    from paddle_tpu.models.ctr import WideDeep, DeepFM

    rng = np.random.RandomState(0)
    batch, fields, dense_dim = 64, 26, 13
    ids = rng.randint(0, 100_000, (batch, fields)).astype("i4")
    dense = rng.rand(batch, dense_dim).astype("f4")
    label = rng.randint(0, 2, (batch, 1)).astype("i4")

    for cls in (WideDeep, DeepFM):
        pt.seed(0)
        fleet = Fleet()
        st = DistributedStrategy()
        st.mesh_shape = {"dp": 2, "mp": 2}
        fleet.init(strategy=st)
        model = cls(sparse_feature_number=100_000, sparse_num_field=fields,
                    dense_feature_dim=dense_dim, embedding_size=16,
                    layer_sizes=(64, 64), sharded=True)
        model = fleet.distributed_model(model)
        table = model.embedding.table if hasattr(model, "embedding") \
            else model.emb.table
        assert tuple(table.weight.data.sharding.spec)[0] == "mp"
        o = fleet.distributed_optimizer(
            optimizer.AdamW(learning_rate=1e-3,
                            parameters=model.parameters()))

        def step(ids, dense, label):
            loss = model.loss(model(ids, dense), label)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        cstep = jit.to_static(step, models=[model], optimizers=[o])
        t = fleet.shard_batch(pt.to_tensor(ids), pt.to_tensor(dense),
                              pt.to_tensor(label))
        losses = [float(cstep(*t).numpy()) for _ in range(6)]
        assert losses[-1] < losses[0], (cls.__name__, losses)
