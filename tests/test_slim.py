"""Slim compression suite: pruning, distillation, NAS, Compressor
(VERDICT r3 #5; reference: contrib/slim/{prune/pruner.py,
distillation/distiller.py, nas/light_nas_strategy.py,
core/compressor.py})."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, slim
from paddle_tpu.nn import functional as F


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype("f4")
    w = rng.randn(8, 4).astype("f4")
    y = np.argmax(x @ w + 0.1 * rng.randn(n, 4), axis=1).astype("i4")
    return pt.to_tensor(x), pt.to_tensor(y)


# ---------------------------------------------------------------------------
# pruning


def test_structure_pruner_idx_and_tensor():
    p = slim.StructurePruner({"*": 0}, {"*": "l1_norm"})
    w = np.array([[3.0, 3.0], [0.1, 0.1], [2.0, 2.0], [0.2, 0.2]], "f4")
    idx = p.cal_pruned_idx("w", w, 0.5)
    assert set(idx) == {1, 3}          # two smallest l1 rows
    pruned = p.prune_tensor(w, idx, 0, lazy=False)
    assert pruned.shape == (2, 2)
    lazy = p.prune_tensor(w, idx, 0, lazy=True)
    assert lazy.shape == w.shape
    assert np.all(lazy[[1, 3]] == 0) and np.all(lazy[[0, 2]] == w[[0, 2]])
    m = p.mask("w", w, 0.5)
    np.testing.assert_array_equal(m[[1, 3]], 0.0)
    np.testing.assert_array_equal(m[[0, 2]], 1.0)


def test_magnitude_prune_finetune_keeps_masks():
    """Prune 50%, finetune — pruned weights stay 0 through training and
    the model still learns."""
    m = _mlp()
    x, y = _data()
    o = optimizer.Adam(learning_rate=5e-3, parameters=m.parameters())
    # brief pretrain
    for _ in range(5):
        loss = F.cross_entropy(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()

    masks = slim.prune_model(m, 0.5)
    assert masks  # both Linear weights pruned
    for name, mask in masks.items():
        sparsity = 1.0 - float(np.asarray(mask).mean())
        assert 0.4 < sparsity < 0.6, (name, sparsity)

    losses = []
    for _ in range(15):
        loss = F.cross_entropy(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    # masked entries are exactly zero in the effective (forward) weights
    m.eval()
    _ = m(x)  # forward applies masks; post-hook restores dense
    w0 = np.asarray(m[0].weight.data)
    mask0 = next(v for k, v in masks.items() if k.startswith("0."))
    # after a forward, the dense weight's masked entries only carry the
    # optimizer's last update on a zero gradient (adam eps drift); the
    # masked forward value is exactly 0
    eff = w0 * np.asarray(mask0)
    assert np.count_nonzero(eff) <= np.count_nonzero(np.asarray(mask0))


def test_prune_model_eval_matches_masked_weights():
    m = _mlp()
    x, _ = _data()
    m.eval()
    masks = slim.prune_model(m, {"0.weight": 0.3})
    assert list(masks) == ["0.weight"]
    ref_w = np.asarray(m[0].weight.data) * np.asarray(masks["0.weight"])
    got = m(x).numpy()
    # manual computation with masked first layer (weights are [in, out])
    h = np.maximum(np.asarray(x.numpy()) @ ref_w +
                   np.asarray(m[0].bias.data), 0)
    want = h @ np.asarray(m[2].weight.data) + np.asarray(m[2].bias.data)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sensitivity_restores_model():
    m = _mlp()
    x, y = _data()

    def eval_fn(model):
        return float(F.cross_entropy(model(x), y).numpy())

    before = {n: np.asarray(p.data).copy()
              for n, p in m.named_parameters()}
    sens = slim.sensitivity(m, eval_fn, ratios=(0.2, 0.8))
    assert sens and all(set(v) == {0.2, 0.8} for v in sens.values())
    # heavier pruning must not IMPROVE the (untrained) loss in general —
    # just check the model was restored bit-exact
    for n, p in m.named_parameters():
        np.testing.assert_array_equal(before[n], np.asarray(p.data))


# ---------------------------------------------------------------------------
# distillation


def test_distill_losses_shapes_and_zero_cases():
    rng = np.random.RandomState(0)
    t = pt.to_tensor(rng.randn(4, 10).astype("f4"))
    assert float(slim.l2_distill(t, t).numpy()) == 0.0
    sl = slim.soft_label_distill(t, t)
    # CE of a distribution with itself = its entropy (> 0)
    assert float(sl.numpy()) > 0.0
    a = pt.to_tensor(rng.randn(2, 3, 4, 4).astype("f4"))
    b = pt.to_tensor(rng.randn(2, 5, 4, 4).astype("f4"))
    fsp = slim.fsp_matrix(a, b)
    assert tuple(fsp.shape) == (2, 3, 5)
    assert float(slim.fsp_distill((a, b), (a, b)).numpy()) == 0.0


def test_distillation_model_trains_student_only():
    teacher = _mlp(seed=1)
    student = _mlp(seed=2)
    x, y = _data()
    # give the teacher some competence
    ot = optimizer.Adam(learning_rate=1e-2,
                        parameters=teacher.parameters())
    for _ in range(30):
        loss = F.cross_entropy(teacher(x), y)
        loss.backward()
        ot.step()
        ot.clear_grad()

    dm = slim.DistillationModel(student, teacher, [
        {"kind": "soft_label", "s": None, "t": None, "weight": 1.0},
        {"kind": "l2", "s": "0", "t": "0", "weight": 0.1},
    ])
    # teacher params are NOT part of the distilled model's params
    dm_param_ids = {id(p) for p in dm.parameters()}
    assert all(id(p) not in dm_param_ids for p in teacher.parameters())

    t_before = [np.asarray(p.data).copy() for p in teacher.parameters()]
    o = optimizer.Adam(learning_rate=5e-3, parameters=dm.parameters())
    losses = []
    for _ in range(20):
        out, dloss = dm(x)
        loss = dloss + 0.5 * F.cross_entropy(out, y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    for before, p in zip(t_before, teacher.parameters()):
        np.testing.assert_array_equal(before, np.asarray(p.data))


# ---------------------------------------------------------------------------
# NAS + Compressor


def test_light_nas_search_improves():
    class Space(slim.SearchSpace):
        def init_tokens(self):
            return [0, 0, 0]

        def range_table(self):
            return [8, 8, 8]

        def create_model(self, tokens=None):
            return tokens

    # reward = sum of tokens; annealing must find something better than 0
    nas = slim.LightNASStrategy(Space(), eval_fn=lambda t: sum(t),
                                search_steps=30, seed=0)
    best, best_r, hist = nas.search()
    assert best_r > 0 and len(hist) == 31


def test_compressor_prune_then_finetune():
    m = _mlp()
    x, y = _data()
    o = optimizer.Adam(learning_rate=5e-3, parameters=m.parameters())

    def train_fn(model, batch):
        bx, by = batch
        loss = F.cross_entropy(model(bx), by)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss.numpy()

    def eval_fn(model):
        return float(F.cross_entropy(model(x), y).numpy())

    strat = slim.PruneStrategy(ratios=0.4, start_epoch=1)
    comp = slim.Compressor(m, o, train_fn=train_fn,
                           train_reader=lambda: [(x, y)] * 5,
                           eval_fn=eval_fn, epochs=3, strategies=[strat])
    model, history = comp.run()
    assert len(history) == 3
    assert strat.masks  # pruning actually happened at epoch 1
    assert history[-1]["metric"] < history[0]["metric"] * 1.5
    # sparsity held at the end
    mask = next(iter(strat.masks.values()))
    assert abs((1.0 - float(np.asarray(mask).mean())) - 0.4) < 0.1
