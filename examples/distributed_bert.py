"""Composed multi-axis BERT training through the fleet API.

    python examples/distributed_bert.py          # 8 local devices
    # multi-host pods: one launch per host with --coordinator, or
    # python -m paddle_tpu.distributed.launch --nproc_per_node 2 <script>

Covers: 5-axis mesh (dp/pp/tp), PipelineStack (pp-sharded encoder trunk
with per-stage recompute), Megatron tp shardings, MoE over ep when
enabled, AdamW with mesh-placed slot state, GSPMD batch sharding."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer as opt, jit, nn
from paddle_tpu.models.bert import BertConfig, BertForPretraining
from paddle_tpu.parallel.fleet import Fleet, DistributedStrategy


def main():
    cfg = BertConfig.tiny(use_recompute=True)   # scale up freely
    pt.seed(0)
    model = BertForPretraining(cfg)

    fleet = Fleet()
    st = DistributedStrategy()
    st.mesh_shape = {"dp": 2, "pp": 2, "tp": 2}
    st.recompute = True
    fleet.init(strategy=st)
    model.bert.encoder = fleet.pipeline_stack(list(model.bert.encoder))
    model = fleet.distributed_model(model)
    o = fleet.distributed_optimizer(
        opt.AdamW(learning_rate=1e-4, parameters=model.parameters()))

    rng = np.random.RandomState(0)
    B, S = 8, 64
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype("i4")
    mlm = np.where(rng.rand(B, S) < 0.15,
                   rng.randint(0, cfg.vocab_size, (B, S)), -1).astype("i4")
    nsp = rng.randint(0, 2, (B,)).astype("i4")

    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp) + \
            nn.moe_aux_loss(model)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    t = fleet.shard_batch(pt.to_tensor(ids), pt.to_tensor(mlm),
                          pt.to_tensor(nsp))
    for i in range(5):
        print(f"step {i}: loss={float(cstep(*t).numpy()):.4f}")


if __name__ == "__main__":
    main()
