"""Train LeNet on MNIST end-to-end — the 60-second tour.

    python examples/train_mnist.py

Covers: hapi datasets + transforms, the multiprocess DataLoader, a
compiled train step (jit.to_static: fwd+bwd+optimizer as ONE donated XLA
computation), and eval."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit, io
from paddle_tpu.nn import functional as F
from paddle_tpu.hapi.datasets import MNIST
from paddle_tpu.hapi.vision import transforms as T
from paddle_tpu.models import LeNet


def main():
    # MNIST arrays arrive already normalized to [-1, 1] (reference
    # mnist reader semantics) — just shape HW -> CHW
    tf = T.Compose([T.Lambda(lambda im: im[..., None]),
                    T.Transpose()])
    train = MNIST(mode="train", transform=tf)
    test = MNIST(mode="test", transform=tf)
    loader = io.DataLoader(train, batch_size=128, shuffle=True,
                           num_workers=2, use_native=False)

    pt.seed(0)
    model = LeNet(num_classes=10)
    o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    for epoch in range(2):
        for i, (xb, yb) in enumerate(loader):
            loss = cstep(pt.to_tensor(xb.astype("f4")),
                         pt.to_tensor(yb.astype("i4")))
            if i % 50 == 0:
                print(f"epoch {epoch} step {i}: "
                      f"loss={float(loss.numpy()):.4f}")

    model.eval()
    xs = np.stack([test[i][0] for i in range(512)]).astype("f4")
    ys = np.asarray([test[i][1] for i in range(512)], "i4")
    pred = model(pt.to_tensor(xs)).numpy().argmax(-1)
    print(f"test accuracy (512 samples): {(pred == ys).mean():.3f}")


if __name__ == "__main__":
    main()
