"""Calibrated int8 inference + portable StableHLO export.

    python examples/int8_inference.py

Covers: Predictor precision modes (bf16 / calibrated int8 with REAL
int8xint8->int32 MXU math), and Predictor.export -> load_exported (the
cross-language serving artifact)."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.inference import Config, Predictor, load_exported


def main():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                          nn.Linear(128, 10))
    x = np.random.RandomState(0).randn(16, 64).astype("f4")

    ref = Predictor(model, Config()).run(x)

    cal = [pt.to_tensor(x)]
    p8 = Predictor(model, Config().enable_int8(cal))
    out8 = p8.run(x)
    err = np.abs(out8 - ref).max() / (np.abs(ref).max() + 1e-9)
    print(f"int8 vs f32 relative max error: {err:.4f}")

    path = os.path.join(tempfile.mkdtemp(), "model.stablehlo")
    Predictor(model, Config()).export(path, x)
    runner = load_exported(path)
    print(f"exported {os.path.getsize(path)} bytes; "
          f"roundtrip max diff: {np.abs(runner(x) - ref).max():.2e}")


if __name__ == "__main__":
    main()
