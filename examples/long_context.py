"""Long-context training: sequence parallelism + flash attention +
recompute working together.

    python examples/long_context.py     # 8 local devices (sp=4 x dp=2)

Three pieces compose here (SURVEY §2 row 30):

1. **Ring attention** shards the SEQUENCE over the `sp` mesh axis:
   each device holds S/sp of the tokens, K/V blocks rotate around the
   ICI ring via `ppermute` while a flash-style online softmax
   accumulates — full S×S attention is never materialized, so max
   context length scales linearly with the number of devices.
2. **Flash attention kernel** handles the per-device blocks on TPU
   (seq-gated: engages above the measured crossover, docs/perf_r04.md).
3. **Recompute** (`jax.checkpoint` under the hood) trades FLOPs for the
   activation memory the long sequence would otherwise pin.

On the CPU demo mesh the numbers are tiny; on a TPU pod slice the same
code runs with real shapes — only mesh_shape and the config change.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    # default: 8-device CPU demo mesh. Set RUN_ON_TPU=1 on a pod host —
    # decided via env, NOT jax.default_backend(), because probing the
    # backend is first-contact and blocks if a device tunnel is wedged
    if not int(os.environ.get("RUN_ON_TPU", "0")):
        if "XLA_FLAGS" not in os.environ:
            os.environ["XLA_FLAGS"] = \
                "--xla_force_host_platform_device_count=8"
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import paddle_tpu as pt
    from paddle_tpu.parallel.ring_attention import ring_attention

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    B, H, S, D = 4, 8, 1024, 64          # seq 1024 split 4-ways over sp
    rng = np.random.RandomState(0)
    q = rng.randn(B, H, S, D).astype("f4")
    k = rng.randn(B, H, S, D).astype("f4")
    v = rng.randn(B, H, S, D).astype("f4")

    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=True).data,
        mesh=mesh,
        in_specs=(P("dp", None, "sp", None),) * 3,
        out_specs=P("dp", None, "sp", None), check_vma=False))
    out = np.asarray(ring(q, k, v))
    print(f"ring attention: seq {S} sharded sp=4, out {out.shape}, "
          f"finite={np.isfinite(out).all()}")

    # parity vs single-device causal attention on a slice
    logits = np.einsum("hqd,hkd->hqk", q[0], k[0]) / np.sqrt(D)
    mask = np.tril(np.ones((S, S), bool))
    e = np.exp(np.where(mask, logits, -1e30) -
               np.where(mask, logits, -1e30).max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("hqk,hkd->hqd", p, v[0])
    err = np.abs(out[0] - ref).max()
    print(f"parity vs full causal attention: max|err|={err:.2e}")

    # the same composition through the user-level model: long-seq BERT
    # with recompute (flash engages automatically on TPU at this length)
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu import optimizer as opt, jit

    pt.seed(0)
    cfg = BertConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     max_position_embeddings=1024, use_recompute=True)
    m = BertForPretraining(cfg)
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    ids = rng.randint(0, 512, (1, 1024)).astype("i4")
    mlm = np.where(rng.rand(1, 1024) < 0.15,
                   rng.randint(0, 512, (1, 1024)), -1).astype("i4")
    nsp = np.zeros((1,), "i4")

    def step(i, ml, ns):
        lo, nl = m(i)
        loss = m.loss(lo, nl, ml, ns)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    f = jit.to_static(step, models=[m], optimizers=[o])
    args = [pt.to_tensor(a) for a in (ids, mlm, nsp)]
    losses = [float(f(*args).numpy()) for _ in range(3)]
    print(f"seq-1024 recompute BERT: loss {losses[0]:.4f} -> "
          f"{losses[-1]:.4f}")


if __name__ == "__main__":
    main()
