"""CTR training end-to-end: the parameter-server workflow, TPU-style.

    python examples/ctr_sharded.py

Covers the full fluid PS-era user journey rebuilt on a mesh:
  * fluid.dataset (DatasetFactory -> InMemoryDataset) parsing MultiSlot
    text files, load_into_memory + local_shuffle,
  * static Program + Executor.train_from_dataset over those batches,
  * then the dygraph/fleet version: WideDeep with its embedding tables
    row-sharded over the mesh's mp axis (the PS replacement,
    parallel/embedding.ShardedEmbedding), AdamW, compiled step.

reference: fluid/incubate/fleet/parameter_server +
python/paddle/fluid/dataset.py CTR examples.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np

import paddle_tpu as pt
from paddle_tpu import fluid, optimizer, static, jit


def write_multislot(path, n=512, fields=8, dense=4, vocab=1000):
    """label-free MultiSlot lines: ids slot, dense slot, label slot."""
    rng = np.random.RandomState(0)
    w = rng.randn(fields)
    with open(path, "w") as fh:
        for _ in range(n):
            ids = rng.randint(0, vocab, fields)
            d = rng.rand(dense)
            y = int((w[ids % fields].sum() + d.sum()) > fields * 0.45)
            fh.write(f"{fields} " + " ".join(map(str, ids)) +
                     f" {dense} " + " ".join(f"{v:.4f}" for v in d) +
                     f" 1 {y}\n")


def static_train_from_dataset(train_file):
    print("== static: Executor.train_from_dataset over fluid.dataset ==")
    pt.enable_static()
    try:
        prog, startup = static.Program(), static.Program()
        with static.program_guard(prog, startup):
            ids = static.data("ids", [None, 8], "int64")
            dense = static.data("dense", [None, 4], "float32")
            label = static.data("label", [None, 1], "float32")
            emb = fluid.layers.embedding(ids, (1000, 8))
            feat = fluid.layers.concat(
                [fluid.layers.reshape(emb, [-1, 64]), dense], axis=1)
            h = fluid.layers.fc(feat, size=32, act="relu")
            logit = fluid.layers.fc(h, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.sigmoid_cross_entropy_with_logits(
                    logit, label))
            optimizer.Adam(learning_rate=1e-2).minimize(loss)

        class V:
            def __init__(self, name, dtype):
                self.name, self.dtype = name, dtype
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(64)
        ds.set_filelist([train_file])
        ds.set_use_var([V("ids", "int64"), V("dense", "float32"),
                        V("label", "float32")])
        ds.load_into_memory()
        ds.local_shuffle()
        exe = static.Executor()
        exe.run(startup)
        for epoch in range(4):
            exe.train_from_dataset(prog, ds, fetch_list=[loss])
            out, = exe.run(prog, feed=next(iter(ds._batches())),
                           fetch_list=[loss])
            print(f"  epoch {epoch}: loss={float(out):.4f}")
    finally:
        pt.disable_static()


def fleet_sharded_widedeep():
    print("== fleet: WideDeep, embedding row-sharded over mp ==")
    from paddle_tpu.models.ctr import WideDeep
    from paddle_tpu.parallel.fleet import Fleet, DistributedStrategy

    pt.seed(0)
    fleet = Fleet()
    st = DistributedStrategy()
    st.mesh_shape = {"dp": 2, "mp": 2}
    fleet.init(strategy=st)
    model = WideDeep(sparse_feature_number=10000, sparse_num_field=8,
                     dense_feature_dim=4, embedding_size=8,
                     layer_sizes=(32, 32), sharded=True)
    model = fleet.distributed_model(model)
    print("  table sharding:",
          model.embedding.table.weight.data.sharding.spec)
    o = fleet.distributed_optimizer(optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters()))

    def step(ids, dense, label):
        loss = model.loss(model(ids, dense), label)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 10000, (32, 8)).astype("i4")
    dense = rng.rand(32, 4).astype("f4")
    label = rng.randint(0, 2, (32, 1)).astype("i4")
    t = fleet.shard_batch(pt.to_tensor(ids), pt.to_tensor(dense),
                          pt.to_tensor(label))
    for i in range(6):
        loss = cstep(*t)
        if i % 2 == 0:
            print(f"  step {i}: loss={float(loss.numpy()):.4f}")


def main():
    with tempfile.TemporaryDirectory() as tmp:
        train_file = os.path.join(tmp, "train.txt")
        write_multislot(train_file)
        static_train_from_dataset(train_file)
    fleet_sharded_widedeep()


if __name__ == "__main__":
    import jax
    if jax.default_backend() != "cpu" and jax.device_count() < 4:
        jax.config.update("jax_platforms", "cpu")
    main()
