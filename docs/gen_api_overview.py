"""Regenerate docs/api_overview.md from the live package:
    python docs/gen_api_overview.py > docs/api_overview.md
"""
import contextlib
import importlib
import io
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import warnings  # noqa: E402

warnings.filterwarnings("ignore")
buf = io.StringIO()
with contextlib.redirect_stderr(buf):
    import paddle_tpu  # noqa: F401,E402

SECTIONS = [
    ("Core", ["paddle_tpu", "paddle_tpu.tensor", "paddle_tpu.autograd",
              "paddle_tpu.dispatch", "paddle_tpu.random",
              "paddle_tpu.device", "paddle_tpu.param_attr"]),
    ("Ops", ["paddle_tpu.ops.math", "paddle_tpu.ops.manip",
             "paddle_tpu.ops.creation", "paddle_tpu.ops.nn_ops",
             "paddle_tpu.ops.loss", "paddle_tpu.ops.sequence",
             "paddle_tpu.ops.crf", "paddle_tpu.ops.ctc",
             "paddle_tpu.ops.detection", "paddle_tpu.ops.control_flow",
             "paddle_tpu.ops.imperative_flow"]),
    ("Pallas kernels", ["paddle_tpu.ops.pallas"]),
    ("Layers", ["paddle_tpu.nn", "paddle_tpu.nn.rnn",
                "paddle_tpu.nn.decode"]),
    ("Training", ["paddle_tpu.optimizer", "paddle_tpu.optimizer.lr",
                  "paddle_tpu.initializer", "paddle_tpu.regularizer",
                  "paddle_tpu.clip", "paddle_tpu.metric",
                  "paddle_tpu.amp", "paddle_tpu.jit",
                  "paddle_tpu.static"]),
    ("Data/IO", ["paddle_tpu.io", "paddle_tpu.reader",
                 "paddle_tpu.dataset", "paddle_tpu.inference",
                 "paddle_tpu.quantization"]),
    ("Distributed", ["paddle_tpu.parallel.collective",
                     "paddle_tpu.parallel.fleet",
                     "paddle_tpu.parallel.megatron",
                     "paddle_tpu.parallel.ring_attention",
                     "paddle_tpu.parallel.embedding",
                     "paddle_tpu.distributed"]),
    ("High-level", ["paddle_tpu.hapi", "paddle_tpu.models",
                    "paddle_tpu.distribution",
                    "paddle_tpu.dygraph_to_static"]),
    ("Compat facades", ["paddle_tpu.fluid", "paddle_tpu.fluid.layers",
                        "paddle_tpu.fluid.dygraph",
                        "paddle_tpu.fluid.contrib",
                        "paddle_tpu.framework", "paddle_tpu.imperative",
                        "paddle_tpu.incubate", "paddle_tpu.compat",
                        "paddle_tpu.sysconfig",
                        "paddle_tpu.common_ops_import"]),
]


def main():
    print("""# API overview

Every public module, with the reference surface it rebuilds. Generated
from the live package (`python docs/gen_api_overview.py` regenerates).
""")
    for title, mods in SECTIONS:
        print(f"## {title}\n")
        for name in mods:
            try:
                m = importlib.import_module(name)
            except Exception as e:  # pragma: no cover
                print(f"- `{name}` — IMPORT FAILED: {e}")
                continue
            doc = (m.__doc__ or "").strip().split("\n")[0]
            pub = [n for n in dir(m) if not n.startswith("_")]
            print(f"- **`{name}`** ({len(pub)} public names) — {doc}")
        print()


if __name__ == "__main__":
    main()
