"""paddle_tpu.random — global PRNG state management.

TPU-native rebuild of the reference's random seed handling
(reference: python/paddle/fluid/framework.py default_startup_program random
seed + paddle/fluid/operators/dropout_op.cu curand streams). CUDA-style
stateful RNG does not exist on TPU/XLA; instead we keep ONE global threaded
PRNG key (a ``jax.random`` key held in a Tensor) and every stochastic op
splits a subkey off it. Because the key lives in a Tensor, ``jit.to_static``
can capture it as carried state: dropout inside a compiled train step splits
the *traced* key and writes the advanced key back, so randomness progresses
correctly across compiled steps instead of being baked in as a constant.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor

_seed_value = 0


class _LazyKeyTensor(Tensor):
    """The global key Tensor, materialized on FIRST USE: building the key
    at import would initialize the XLA backend, which must not happen
    before a multi-host child calls jax.distributed.initialize()
    (distributed/launch.py imports this package before the worker
    script runs)."""

    __slots__ = ()

    def __init__(self):
        Tensor.data.__set__(self, None)

    def _materialize(self):
        # run the CANONICAL Tensor.__init__ now — it fills every slot the
        # same way any Tensor gets them, so this class never has to
        # mirror tensor.py's field list
        Tensor.__init__(self, jax.random.PRNGKey(_seed_value),
                        stop_gradient=True, name="global_rng_key")

    def __getattr__(self, name):
        # a slot unset because we have not materialized yet (e.g.
        # stop_gradient read before first key use): materialize + retry
        if name.startswith("__"):
            raise AttributeError(name)
        self._materialize()
        return object.__getattribute__(self, name)

    @property
    def data(self):
        d = Tensor.data.__get__(self)
        if d is None:
            self._materialize()
            d = Tensor.data.__get__(self)
        return d

    @data.setter
    def data(self, value):
        Tensor.data.__set__(self, value)


# the global key lives in a Tensor so mode transforms can swap its payload
_global_key = _LazyKeyTensor()


def seed(value: int):
    """Set the global seed (paddle.seed / fluid.default_main_program
    random_seed equivalent). Stays lazy: the key materializes on first
    use, so seeding at program start keeps the backend untouched."""
    global _seed_value
    _seed_value = int(value)
    Tensor.data.__set__(_global_key, None)  # re-derive from the new seed
    return _seed_value


def get_seed():
    return _seed_value


def global_key_tensor() -> Tensor:
    """The Tensor holding the global key — exposed so to_static can thread
    it through compiled steps as mutable state."""
    return _global_key


def next_key():
    """Split a fresh subkey off the global key, advancing it."""
    key, sub = jax.random.split(_global_key.data)
    _global_key.data = key
    return sub


def next_key_graph():
    """Key for a stochastic *op*: in static-graph mode returns a symbolic
    key variable that the Executor feeds with a fresh subkey on every run
    (so recorded dropout masks differ across runs — the reference gets this
    from stateful curand; XLA needs the key threaded as an input). In
    dygraph, a concrete subkey."""
    from . import dispatch
    if dispatch.in_static_mode():
        from .static import make_rng_var
        return make_rng_var()
    return next_key()


def split_keys(n):
    keys = jax.random.split(_global_key.data, n + 1)
    _global_key.data = keys[0]
    return keys[1:]
