"""paddle_tpu.amp — automatic mixed precision.

TPU-native rebuild of reference python/paddle/fluid/contrib/mixed_precision
(decorate / AutoMixedPrecisionLists / loss scaling). On TPU the native
16-bit format is bfloat16 — same exponent range as fp32 — so the default
policy is bf16 compute with NO loss scaling (the fp16 dynamic scaler is
provided for parity and for float16 experiments).

``auto_cast`` flips a global flag read by the white-listed ops (matmul,
conv, linear, einsum-based attention): inputs are cast to the compute dtype
at the op boundary, and params stay fp32 (master weights) — the standard
TPU recipe, and what the reference's black/white lists approximate.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from .tensor import Tensor

_state = {"enabled": False, "dtype": jnp.bfloat16}


def is_enabled():
    return _state["enabled"]


def compute_dtype():
    return _state["dtype"]


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """reference: fluid.contrib.mixed_precision.decorate → context form."""
    prev = dict(_state)
    _state["enabled"] = enable
    _state["dtype"] = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
    try:
        yield
    finally:
        _state.update(prev)


amp_guard = auto_cast


def maybe_cast(*arrays):
    """Cast inputs to the AMP compute dtype when autocast is active —
    called by white-listed ops (matmul/conv/linear)."""
    if not _state["enabled"]:
        return arrays
    dt = _state["dtype"]
    out = []
    for a in arrays:
        if a is not None and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != dt:
            a = a.astype(dt)
        out.append(a)
    return tuple(out)


class GradScaler:
    """reference: mixed_precision loss scaling (incr/decr dynamic scheme).
    Needed only for float16; bf16 trains unscaled on TPU."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._good = 0
        self._bad = 0

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import numpy as np
        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._params():
            if p._grad is not None:
                g = p._grad * inv
                finite = bool(jax.device_get(jnp.all(jnp.isfinite(g))))
                if not finite:
                    found_inf = True
                p._grad = g
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not hasattr(self, "_found_inf"):
            self.unscale_(optimizer)
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale *= self._decr_ratio
                self._bad = 0
            optimizer.clear_grad()
        else:
            optimizer.step()
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0
        del self._found_inf

    def minimize(self, optimizer, scaled_loss):
        if scaled_loss is not None and scaled_loss._tape_node is not None:
            scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        pass

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def set_state_dict(self, s):
        self._scale, self._good, self._bad = s["scale"], s["good"], s["bad"]


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16"):
    """paddle.amp.decorate parity: for O2, cast model params to the compute
    dtype (pure bf16); for O1 leave params fp32 and rely on auto_cast."""
    if level == "O2" and models is not None:
        ms = models if isinstance(models, (list, tuple)) else [models]
        for m in ms:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers
