"""paddle_tpu.amp — automatic mixed precision.

TPU-native rebuild of reference python/paddle/fluid/contrib/mixed_precision
(decorate / AutoMixedPrecisionLists / loss scaling). On TPU the native
16-bit format is bfloat16 — same exponent range as fp32 — so the default
policy is bf16 compute with NO loss scaling (the fp16 dynamic scaler is
provided for parity and for float16 experiments).

``auto_cast`` flips a global flag read by the white-listed ops (matmul,
conv, linear, einsum-based attention): inputs are cast to the compute dtype
at the op boundary, and params stay fp32 (master weights) — the standard
TPU recipe, and what the reference's black/white lists approximate.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from .tensor import Tensor

_state = {"enabled": False, "dtype": jnp.bfloat16}


def is_enabled():
    return _state["enabled"]


def compute_dtype():
    return _state["dtype"]


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """reference: fluid.contrib.mixed_precision.decorate → context form."""
    prev = dict(_state)
    _state["enabled"] = enable
    _state["dtype"] = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
    try:
        yield
    finally:
        _state.update(prev)


amp_guard = auto_cast


def tree_all_finite(arrays):
    """ONE fused all-finite reduction over a list of arrays/Tensors
    (None entries skipped) — a device bool scalar, no host sync, safe
    under jit. The finite-check machinery shared by
    :meth:`GradScaler.unscale_` and the resilience NaN guard
    (paddle_tpu.resilience.guard)."""
    finite = jnp.asarray(True)
    for a in arrays:
        if a is None:
            continue
        if isinstance(a, Tensor):
            a = a.data
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(a)))
    return finite


def maybe_cast(*arrays):
    """Cast inputs to the AMP compute dtype when autocast is active —
    called by white-listed ops (matmul/conv/linear)."""
    if not _state["enabled"]:
        return arrays
    dt = _state["dtype"]
    out = []
    for a in arrays:
        if a is not None and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != dt:
            a = a.astype(dt)
        out.append(a)
    return tuple(out)


class GradScaler:
    """reference: mixed_precision loss scaling (incr/decr dynamic scheme).
    Needed only for float16; bf16 trains unscaled on TPU.

    Jit-safe design: scale / good / bad counters and the found-inf flag are
    device scalars, found-inf is ONE fused all-finite reduction over every
    grad (no per-parameter host sync), and a skipped step is expressed as a
    ``jnp.where`` select back to the pre-step params/slots — so the whole
    scaler composes with ``jit.to_static`` (the scaler state rides along as
    carried Tensors)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1):
        self._enable = enable
        self._scale = Tensor(jnp.asarray(init_loss_scaling, jnp.float32),
                             name="loss_scale")
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._good = Tensor(jnp.zeros((), jnp.int32), name="scaler_good")
        self._bad = Tensor(jnp.zeros((), jnp.int32), name="scaler_bad")

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * Tensor(self._scale.data)

    def unscale_(self, optimizer):
        """Divide grads by the scale and compute found-inf as a single
        fused on-device reduction (no host sync, jit-safe)."""
        if not self._enable:
            return
        inv = 1.0 / self._scale.data
        finite = tree_all_finite(
            [p._grad for p in optimizer._params() if p._grad is not None])
        for p in optimizer._params():
            if p._grad is not None:
                p._grad = p._grad * inv
        self._found_inf = jnp.logical_not(finite)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not hasattr(self, "_found_inf"):
            self.unscale_(optimizer)
        found = self._found_inf  # device bool scalar

        # snapshot, step unconditionally, then select old state back if inf
        # (slots must exist BEFORE the snapshot or a rolled-back first step
        # would leave lazily-created accumulators holding the inf update)
        optimizer._ensure_all_slots()
        params = [p for p in optimizer._params() if p._grad is not None]
        old_params = [p.data for p in params]
        old_slots = [(t, t.data)
                     for slots in optimizer._accumulators.values()
                     for t in slots.values()]
        optimizer.step()
        for p, old in zip(params, old_params):
            p.data = jnp.where(found, old, p.data)
        for t, old in old_slots:
            t.data = jnp.where(found, old, t.data)

        # dynamic scale bookkeeping, all on device
        good = jnp.where(found, 0, self._good.data + 1)
        bad = jnp.where(found, self._bad.data + 1, 0)
        scale = self._scale.data
        scale = jnp.where(bad >= self._decr_every, scale * self._decr_ratio,
                          jnp.where(good >= self._incr_every,
                                    scale * self._incr_ratio, scale))
        self._good.data = jnp.where(good >= self._incr_every, 0, good)
        self._bad.data = jnp.where(bad >= self._decr_every, 0, bad)
        self._scale.data = scale
        del self._found_inf

    def minimize(self, optimizer, scaled_loss):
        if scaled_loss is not None and scaled_loss._tape_node is not None:
            scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        pass

    def state_dict(self):
        return {"scale": float(jax.device_get(self._scale.data)),
                "good": int(jax.device_get(self._good.data)),
                "bad": int(jax.device_get(self._bad.data))}

    def set_state_dict(self, s):
        self._scale.data = jnp.asarray(s["scale"], jnp.float32)
        self._good.data = jnp.asarray(s["good"], jnp.int32)
        self._bad.data = jnp.asarray(s["bad"], jnp.int32)


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16"):
    """paddle.amp.decorate parity: for O2, cast model params to the compute
    dtype (pure bf16); for O1 leave params fp32 and rely on auto_cast."""
    if level == "O2" and models is not None:
        ms = models if isinstance(models, (list, tuple)) else [models]
        for m in ms:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers
