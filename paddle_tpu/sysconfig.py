"""paddle_tpu.sysconfig — installation paths.

Reference: python/paddle/sysconfig.py (get_include/get_lib for building
C++ extensions against the installed package). Here the native surface
is the csrc host runtime; get_lib points at its build output.
"""
import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory containing the package's native headers (csrc)."""
    return os.path.join(_PKG, "csrc")


def get_lib():
    """Directory containing the built native library (libpaddle_tpu
    host runtime, built via csrc/Makefile)."""
    return os.path.join(_PKG, "csrc", "build")
