"""paddle_tpu.clip — gradient clipping.

TPU-native rebuild of reference python/paddle/fluid/clip.py
(GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm). Pure
functional over jnp arrays so the clip fuses into the compiled update step.
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    """reference: GradientClipByValue."""

    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        return [(p, None if g is None else jnp.clip(g, self.min, self.max))
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    """reference: GradientClipByNorm — per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, None))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: GradientClipByGlobalNorm — one scale from the global norm
    of all grads (single fused reduction under jit)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        sq = [jnp.sum(jnp.square(g)) for _, g in params_grads
              if g is not None]
        if not sq:
            return params_grads
        gnorm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return [(p, None if g is None else g * scale)
                for p, g in params_grads]


# fluid aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm):
    """torch-style helper used in some book examples."""
    grads = [(p, p._grad) for p in parameters if p._grad is not None]
    clipped = ClipGradByGlobalNorm(max_norm)(grads)
    for (p, _), (_, g) in zip(grads, clipped):
        p._grad = g


class ErrorClipByValue:
    """reference: fluid/clip.py ErrorClipByValue — clips the ERROR
    (gradient of a specific var) during backward. Attach via
    `var.error_clip = ErrorClipByValue(max=...)`; the tape applies it to
    that tensor's incoming gradient."""

    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grad):
        return jnp.clip(grad, self.min, self.max)


def set_gradient_clip(clip, param_list=None, program=None):
    """reference: fluid/clip.py set_gradient_clip. With param_list, the
    strategy attaches to those parameters only (the optimizer applies it
    per-param before its own clip); otherwise it becomes the global
    default every optimizer without an explicit grad_clip uses."""
    if param_list:
        for p in param_list:
            p.grad_clip = clip
        return clip
    global _global_grad_clip
    _global_grad_clip = clip
    return clip


_global_grad_clip = None


def get_gradient_clip():
    return _global_grad_clip
