"""AST-based dygraph→static conversion (ProgramTranslator).

TPU-native rebuild of the reference's dygraph_to_static
(reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:249 ProgramTranslator,
ifelse_transformer.py / loop_transformer.py). The reference rewrites
Python `if`/`while` whose predicates are Variables into cond/while ops in
a static Program; here the rewrite targets `lax.cond`/`lax.while_loop`
through ops.control_flow — which already run plain Python when the
predicate is concrete, so transformed code behaves identically in eager
mode and becomes compiled control flow under `jit.to_static` tracing
(where a plain Python `if` would silently bake one branch).

Transform scope (the reference's core cases):
* ``if``/``elif``/``else`` statements → ``convert_ifelse`` with the
  branch-assigned names threaded as explicit operands,
* ``while`` loops → ``convert_while`` with the body-assigned names as
  loop carry; ``break``/``continue`` are lowered first to guard flags
  (reference: break_continue_transformer.py) — the loop condition gains
  ``not _brk`` and statements after a potential break/continue point are
  wrapped in ``if not (_brk or _cont)`` blocks, all tensor-aware,
* ``for`` over ``range(...)`` or a sequence/Tensor → an index-carrying
  ``while`` (reference: loop_transformer.py); a ``range`` with traced
  bounds compiles to ``lax.while_loop``, python iterables keep eager
  python-loop semantics (the trace unrolls them exactly as before),
* ``and`` / ``or`` / ``not`` inside the converted predicates →
  ``convert_and/or/not`` (tensor-aware, short-circuit preserved for
  Python values).

Functions whose source can't be rewritten (no source, exotic syntax)
fall back to trace-only conversion with a debug log — matching the
reference's "don't transform what you can't prove" behavior.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import numpy as np
import jax

from .tensor import Tensor
from .utils.log import get_logger

_log = get_logger("paddle_tpu.d2s")


class _Undefined:
    """Name not bound on (at least) one path into a converted region."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"<undefined {self.name}>"


def ld(thunk, name):
    """Load a possibly-unbound local for use as a branch/loop operand."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return _Undefined(name)


def _is_tensorish(x):
    return isinstance(x, (Tensor, jax.Array)) or isinstance(
        x, jax.core.Tracer)


def convert_ifelse(pred, true_fn, false_fn, names, operands,
                   fresh_flags=None):
    """Runtime dispatch for a rewritten `if`. fresh_flags marks operands
    that both branches assign before reading — those may enter undefined
    (a placeholder is threaded; it is provably never read)."""
    if isinstance(pred, Tensor):
        concrete = not isinstance(pred.data, jax.core.Tracer)
    elif _is_tensorish(pred):
        concrete = not isinstance(pred, jax.core.Tracer)
    else:
        return true_fn(*operands) if pred else false_fn(*operands)
    if concrete:
        taken = bool(np.asarray(jax.device_get(
            pred.data if isinstance(pred, Tensor) else pred)).item())
        return true_fn(*operands) if taken else false_fn(*operands)
    fresh_flags = fresh_flags or (False,) * len(operands)
    ops_in = []
    for v, n, fresh in zip(operands, names, fresh_flags):
        if isinstance(v, _Undefined):
            if not fresh:
                raise ValueError(
                    f"to_static if-conversion: variable '{n}' must be "
                    "defined before a tensor-dependent `if` (a branch "
                    "reads it, or only one branch assigns it)")
            v = np.float32(0.0)  # never read: both branches overwrite
        ops_in.append(v)
    from .ops.control_flow import cond as _cond
    return _cond(pred, true_fn, false_fn, tuple(ops_in))


def convert_while(cond_fn, body_fn, names, operands):
    """Runtime dispatch for a rewritten `while`.

    Re-probes the condition EVERY iteration: a loop can start with
    concrete python carries (run eagerly) and turn tensor-dependent
    mid-loop — e.g. a lowered `break` flag that becomes a traced bool the
    first time its guard fires — at which point the remaining iterations
    defer to lax.while_loop with the current values as carry."""
    def _go_lax(vals):
        for v, n in zip(vals, names):
            if isinstance(v, _Undefined):
                raise ValueError(
                    f"to_static while-conversion: loop variable '{n}' "
                    "must be initialized before a tensor-dependent "
                    "`while`")
        from .ops.control_flow import while_loop as _while
        out = _while(cond_fn, body_fn, list(vals))
        return tuple(out) if isinstance(out, (tuple, list)) else (out,)

    vals = tuple(operands)
    while True:
        probe = cond_fn(*vals)
        if isinstance(probe, Tensor):
            if isinstance(probe.data, jax.core.Tracer):
                return _go_lax(vals)
            taken = bool(np.asarray(jax.device_get(probe.data)).item())
        elif _is_tensorish(probe):
            if isinstance(probe, jax.core.Tracer):
                return _go_lax(vals)
            taken = bool(np.asarray(jax.device_get(probe)).item())
        else:
            taken = bool(probe)
        if not taken:
            return vals
        out = body_fn(*vals)
        vals = out if isinstance(out, tuple) else (out,)


def convert_and(a_thunk, b_thunk):
    a = a_thunk()
    if not (_is_tensorish(a) or isinstance(a, Tensor)):
        return a and b_thunk()
    from .ops import math as M
    return M.logical_and(_as_bool(a), _as_bool(b_thunk()))


def convert_or(a_thunk, b_thunk):
    a = a_thunk()
    if not (_is_tensorish(a) or isinstance(a, Tensor)):
        return a or b_thunk()
    from .ops import math as M
    return M.logical_or(_as_bool(a), _as_bool(b_thunk()))


def convert_not(a):
    if not (_is_tensorish(a) or isinstance(a, Tensor)):
        return not a
    from .ops import math as M
    return M.logical_not(_as_bool(a))


def _as_bool(x):
    from .ops import math as M
    if isinstance(x, Tensor) and x.data.dtype != jax.numpy.bool_:
        return M.cast(x, "bool")
    return x


def convert_for_seq(it):
    """Normalize a for-loop iterable ONCE (assigned in the conversion's
    prelude): Tensors and random-access sequences pass through;
    enumerate/zip/generators and other len-less iterables materialize to
    a list — the loop body then indexes without per-iteration copies.
    (Deviation: an INFINITE generator can no longer be broken out of —
    the reference's loop_transformer has the same constraint.)"""
    if isinstance(it, Tensor) or _is_tensorish(it):
        return it
    if hasattr(it, "__len__") and hasattr(it, "__getitem__"):
        return it
    return list(it)


def convert_for_len(it):
    """Loop length for a for→while conversion. Tensor leading dims are
    static under jax, so this is a python int for everything but a traced
    scalar range bound (handled by convert_range_len)."""
    if isinstance(it, Tensor):
        return int(it.shape[0])
    if _is_tensorish(it):
        return int(it.shape[0])
    return len(it)


def convert_for_item(it, i):
    """it[i]; tolerates the pre-loop init probe on empty sequences."""
    if not (isinstance(it, Tensor) or _is_tensorish(it)):
        if len(it) == 0:
            return None  # loop body never runs; placeholder only
        if isinstance(i, Tensor):
            if isinstance(i.data, jax.core.Tracer):
                raise ValueError(
                    "to_static for-conversion: a tensor-dependent loop "
                    "index over a PYTHON sequence cannot compile — make "
                    "the iterable a Tensor (stack it) or keep the exit "
                    "condition concrete")
            i = int(np.asarray(jax.device_get(i.data)).item())
        return it[int(i)]
    return it[i]


def convert_range_len(*args):
    """len(range(start, stop, step)) for int OR Tensor bounds."""
    if all(isinstance(a, (int, np.integer)) for a in args):
        return len(range(*[int(a) for a in args]))
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    else:
        start, stop, step = args
    if not isinstance(step, (int, np.integer)):
        raise ValueError("to_static for-range: a traced STEP is not "
                         "supported (start/stop may be tensors)")
    step = int(step)
    if step == 0:
        raise ValueError("range() step must not be zero")
    # ceil((stop-start)/step) clamped at 0, in tensor arithmetic
    from .ops import math as M
    n = (stop - start + (step - 1 if step > 0 else step + 1)) // step
    if isinstance(n, Tensor) or _is_tensorish(n):
        return M.maximum(n, 0)
    return max(int(n), 0)


# ---------------------------------------------------------------------------
# AST rewriting

class _AssignedNames(ast.NodeVisitor):
    """Names bound by simple assignments inside a statement list."""

    def __init__(self):
        self.names = set()

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)

    # nested defs keep their own scope
    def visit_FunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _reads_before_write(stmts, name):
    """True when `name` is loaded before any statement stores it (per-
    statement granularity; an Assign's value loads count as reads)."""
    stored = False
    for stmt in stmts:
        loads = False
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Load):
                loads = True
            # `x += 1` reads x even though the target ctx is Store
            if isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id == name:
                loads = True
        if loads and not stored:
            return True
        if _assigned([stmt]) & {name}:
            stored = True
    return False


def _has_break(stmts):
    class V(ast.NodeVisitor):
        found = False

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

        def visit_While(self, node):
            pass  # inner loops own their breaks

        def visit_For(self, node):
            pass

        def visit_FunctionDef(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _has_early_return(stmts):
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_FunctionDef(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_break_or_continue(stmt):
    """break/continue at THIS loop's level inside one statement (nested
    loops own theirs) — the single-statement view of _has_break."""
    return _has_break([stmt])


def _flag_guard_test(brk, cont):
    """`not (<brk> or <cont>)` as AST (BoolOp-rewritten later)."""
    return ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
        op=ast.Or(), values=[ast.Name(id=brk, ctx=ast.Load()),
                             ast.Name(id=cont, ctx=ast.Load())]))


def _set_flag(name):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value=True))


class _CannotLower(Exception):
    """break/continue buried where the lowering can't guard (with/try)."""


def _lower_break_continue(stmts, brk, cont):
    """Rewrite a loop body (reference: break_continue_transformer.py):
    `break`/`continue` become flag assignments, and every statement that
    could execute after a flag was set is wrapped in
    `if not (brk or cont): ...` — so the lowered body is flag-pure and
    the surrounding while converts through the ordinary path."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_set_flag(brk))
            break  # statically unreachable afterwards
        if isinstance(s, ast.Continue):
            out.append(_set_flag(cont))
            break
        if _contains_break_or_continue(s):
            if isinstance(s, ast.If):
                s = ast.If(test=s.test,
                           body=_lower_break_continue(s.body, brk, cont),
                           orelse=_lower_break_continue(s.orelse, brk,
                                                        cont)
                           if s.orelse else [])
            else:
                raise _CannotLower(ast.dump(s)[:80])
            # anything after this statement runs only if no flag fired
            out.append(s)
            rest = _lower_break_continue(stmts[i + 1:], brk, cont)
            if rest:
                out.append(ast.If(test=_flag_guard_test(brk, cont),
                                  body=rest, orelse=[]))
            return out
        out.append(s)
    return out


class _BoolOpRewriter(ast.NodeTransformer):
    """and/or/not → tensor-aware converters (inside predicates)."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "_jst_and" if isinstance(node.op, ast.And) else "_jst_or"
        expr = node.values[-1]
        for left in reversed(node.values[:-1]):
            expr = ast.Call(
                func=ast.Name(id=op, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=left),
                      ast.Lambda(args=_empty_args(), body=expr)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=ast.Name(id="_jst_not", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while statements into converter calls."""

    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    # -- if ----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_early_return(node.body) or _has_early_return(node.orelse):
            return node  # early returns keep python semantics
        body_assigned = _assigned(node.body)
        else_assigned = _assigned(node.orelse)
        out_names = sorted(body_assigned | else_assigned)
        fresh = tuple(
            n in body_assigned and n in else_assigned and
            not _reads_before_write(node.body, n) and
            not _reads_before_write(node.orelse, n)
            for n in out_names)
        uid = self._uid()
        test = _BoolOpRewriter().visit(node.test)
        tname, fname = f"_jst_true_{uid}", f"_jst_false_{uid}"
        args = ast.arguments(
            posonlyargs=[], vararg=None, kwonlyargs=[], kw_defaults=[],
            kwarg=None, defaults=[],
            args=[ast.arg(arg=n) for n in out_names])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in out_names],
            ctx=ast.Load()))
        true_def = ast.FunctionDef(
            name=tname, args=args, body=list(node.body) + [ret],
            decorator_list=[], returns=None, type_params=[])
        false_def = ast.FunctionDef(
            name=fname, args=args,
            body=(list(node.orelse) if node.orelse else []) + [ret],
            decorator_list=[], returns=None, type_params=[])
        loads = [_ld_expr(n) for n in out_names]
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in out_names],
                ctx=ast.Store())] if out_names else
            [ast.Name(id=f"_jst_void_{uid}", ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="_jst_ifelse", ctx=ast.Load()),
                args=[test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in out_names], ctx=ast.Load()),
                      ast.Tuple(elts=loads, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=b)
                                      for b in fresh], ctx=ast.Load())],
                keywords=[]))
        if not out_names:
            # still execute for side-effect-free parity; keep simple form
            call = ast.Expr(value=call.value)
        return [true_def, false_def, call]

    # -- while -------------------------------------------------------------
    def visit_While(self, node):
        if node.orelse or _has_early_return(node.body):
            self.generic_visit(node)
            return node  # python semantics kept (logged by caller)
        prelude = []
        if _has_break(node.body):
            # lower break/continue to guard flags FIRST (the guards are
            # plain `if`s the visitor below then converts tensor-aware)
            uid = self._uid()
            brk, cont = f"_jst_brk_{uid}", f"_jst_cont_{uid}"
            try:
                body = _lower_break_continue(list(node.body), brk, cont)
            except _CannotLower:
                self.generic_visit(node)
                return node
            reset_cont = ast.Assign(
                targets=[ast.Name(id=cont, ctx=ast.Store())],
                value=ast.Constant(value=False))
            test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(),
                            operand=ast.Name(id=brk, ctx=ast.Load())),
                node.test])
            node = ast.While(test=test, body=[reset_cont] + body,
                             orelse=[])
            # both flags enter the loop carry -> both need pre-loop inits
            prelude = [ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=ast.Constant(value=False))
                for name in (brk, cont)]
        self.generic_visit(node)
        carry = sorted(_assigned(node.body))
        if not carry:
            return (prelude + [node]) if prelude else node
        uid = self._uid()
        test = _BoolOpRewriter().visit(node.test)
        cname, bname = f"_jst_cond_{uid}", f"_jst_body_{uid}"
        args = ast.arguments(
            posonlyargs=[], vararg=None, kwonlyargs=[], kw_defaults=[],
            kwarg=None, defaults=[],
            args=[ast.arg(arg=n) for n in carry])
        cond_def = ast.FunctionDef(
            name=cname, args=args, body=[ast.Return(value=test)],
            decorator_list=[], returns=None, type_params=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in carry],
            ctx=ast.Load()))
        body_def = ast.FunctionDef(
            name=bname, args=args, body=list(node.body) + [ret],
            decorator_list=[], returns=None, type_params=[])
        loads = [_ld_expr(n) for n in carry]
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carry],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="_jst_while", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in carry], ctx=ast.Load()),
                      ast.Tuple(elts=loads, ctx=ast.Load())],
                keywords=[]))
        return prelude + [cond_def, body_def, call]

    # -- for ---------------------------------------------------------------
    def visit_For(self, node):
        """for → index-carrying while (reference: loop_transformer.py).
        range(...) iterates by arithmetic on (possibly traced) bounds;
        other iterables go through convert_for_len/item, which keeps
        python-loop semantics for python sequences (static trace unroll)
        and row iteration for Tensors."""
        if node.orelse or _has_early_return(node.body):
            self.generic_visit(node)
            return node
        uid = self._uid()
        i_name = f"_jst_i_{uid}"
        n_name = f"_jst_n_{uid}"
        prelude = []

        def assign(name, value):
            return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                              value=value)

        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords)
        if is_range:
            rargs = node.iter.args
            names = []
            for j, a in enumerate(rargs):
                rn = f"_jst_r_{uid}_{j}"
                prelude.append(assign(rn, a))
                names.append(rn)
            prelude.append(assign(n_name, ast.Call(
                func=ast.Name(id="_jst_range_len", ctx=ast.Load()),
                args=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                keywords=[])))
            if len(names) == 1:
                start, step = ast.Constant(value=0), ast.Constant(value=1)
            else:
                start = ast.Name(id=names[0], ctx=ast.Load())
                step = ast.Name(id=names[2], ctx=ast.Load()) \
                    if len(names) == 3 else ast.Constant(value=1)
            item = ast.BinOp(
                left=start, op=ast.Add(),
                right=ast.BinOp(left=step, op=ast.Mult(),
                                right=ast.Name(id=i_name, ctx=ast.Load())))
            init_item = start
        else:
            it_name = f"_jst_it_{uid}"
            prelude.append(assign(it_name, ast.Call(
                func=ast.Name(id="_jst_for_seq", ctx=ast.Load()),
                args=[node.iter], keywords=[])))
            prelude.append(assign(n_name, ast.Call(
                func=ast.Name(id="_jst_for_len", ctx=ast.Load()),
                args=[ast.Name(id=it_name, ctx=ast.Load())],
                keywords=[])))
            item = ast.Call(
                func=ast.Name(id="_jst_for_item", ctx=ast.Load()),
                args=[ast.Name(id=it_name, ctx=ast.Load()),
                      ast.Name(id=i_name, ctx=ast.Load())],
                keywords=[])
            init_item = ast.Call(
                func=ast.Name(id="_jst_for_item", ctx=ast.Load()),
                args=[ast.Name(id=it_name, ctx=ast.Load()),
                      ast.Constant(value=0)],
                keywords=[])
        prelude.append(assign(i_name, ast.Constant(value=0)))
        # init the target before the loop so convert_while's carry check
        # passes (never observed when the loop runs zero times)
        prelude.append(ast.Assign(targets=[node.target], value=init_item))
        target_assign = ast.Assign(
            targets=[node.target],
            value=item)
        incr = assign(i_name, ast.BinOp(
            left=ast.Name(id=i_name, ctx=ast.Load()), op=ast.Add(),
            right=ast.Constant(value=1)))
        test = ast.Compare(left=ast.Name(id=i_name, ctx=ast.Load()),
                           ops=[ast.Lt()],
                           comparators=[ast.Name(id=n_name,
                                                 ctx=ast.Load())])
        body = list(node.body)
        if _has_break(body):
            # lower break/continue HERE (not in visit_While) so the index
            # increment stays OUTSIDE the guards: `continue` must skip
            # the rest of the body but still advance the index
            brk, cont = f"_jst_brk_{uid}", f"_jst_cont_{uid}"
            try:
                body = _lower_break_continue(body, brk, cont)
            except _CannotLower:
                self.generic_visit(node)
                return node
            body = [assign(cont, ast.Constant(value=False))] + body
            prelude.append(assign(brk, ast.Constant(value=False)))
            prelude.append(assign(cont, ast.Constant(value=False)))
            test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(),
                            operand=ast.Name(id=brk, ctx=ast.Load())),
                test])
        loop = ast.While(test=test,
                         body=[target_assign] + body + [incr],
                         orelse=[])
        out = self.visit_While(loop)
        return prelude + (out if isinstance(out, list) else [out])


def _ld_expr(name):
    """`_jst_ld(lambda: <name>, '<name>')` — tolerates unbound names."""
    return ast.Call(
        func=ast.Name(id="_jst_ld", ctx=ast.Load()),
        args=[ast.Lambda(args=_empty_args(),
                         body=ast.Name(id=name, ctx=ast.Load())),
              ast.Constant(value=name)],
        keywords=[])


_HELPERS = {
    "_jst_ifelse": convert_ifelse,
    "_jst_while": convert_while,
    "_jst_and": convert_and,
    "_jst_or": convert_or,
    "_jst_not": convert_not,
    "_jst_ld": ld,
    "_jst_for_seq": convert_for_seq,
    "_jst_for_len": convert_for_len,
    "_jst_for_item": convert_for_item,
    "_jst_range_len": convert_range_len,
}


def _needs_transform(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While, ast.For)):
            return True
    return False


def convert_function(fn):
    """AST-convert a python function for tensor-dependent control flow.
    Returns the rewritten function, or `fn` unchanged when nothing needs
    rewriting / the source can't be processed."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        _log.debug("to_static: no source for %r; trace-only", fn)
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if not _needs_transform(fdef):
        return fn
    fdef.decorator_list = []  # decorators already applied to `fn`
    try:
        new_tree = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new_tree)
        code = compile(new_tree, f"<to_static {fn.__name__}>", "exec")
    except Exception as e:  # pragma: no cover - defensive
        _log.debug("to_static: transform failed for %r (%s); trace-only",
                   fn, e)
        return fn
    glb = dict(fn.__globals__)
    glb.update(_HELPERS)
    # freevars: rebind the closure's current cell values as globals (the
    # documented limitation: converted functions see a snapshot of
    # closed-over names)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    loc = {}
    exec(code, glb, loc)
    new_fn = loc[fn.__name__]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__wrapped_original__ = fn
    return new_fn


class ProgramTranslator:
    """reference: program_translator.py:249 — global enable switch."""

    _instance = None
    enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, flag=True):
        type(self).enabled = bool(flag)

    @classmethod
    def is_enabled(cls):
        return cls.enabled
