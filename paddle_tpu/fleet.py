"""paddle_tpu.fleet — top-level alias of the fleet API (reference:
python/paddle/fleet/__init__.py, an empty placeholder in this
generation; the working implementation lives in
incubate/fleet → here parallel/fleet.py).

The module DELEGATES unknown attributes to the Fleet singleton, so both
spellings work identically:

    from paddle_tpu import fleet
    fleet.init(strategy=st)
    model = fleet.distributed_model(model)   # singleton method
"""
from .parallel.fleet import (fleet, init, Fleet,  # noqa: F401
                             DistributedStrategy, PaddleCloudRoleMaker,
                             UserDefinedRoleMaker, DistributedOptimizer,
                             megatron_param_spec)


def __getattr__(name):
    # any Fleet method/property (distributed_model, shard_batch, mesh,
    # pipeline_stack, save_persistables, ...) resolves on the singleton
    return getattr(fleet, name)
