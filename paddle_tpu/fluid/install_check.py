"""fluid.install_check — the 2-line sanity entry point users run first
(reference: python/paddle/fluid/install_check.py:46 run_check — builds a
tiny Linear model and runs one step single- and multi-device).

    import paddle_tpu.fluid as fluid
    fluid.install_check.run_check()
"""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    """Train a 2-param linear model one step eagerly, one step compiled,
    and (when >1 device is visible) one data-parallel step on a dp mesh —
    the TPU analogues of the reference's simple-exe and parallel-exe
    checks."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit
    from paddle_tpu.nn import functional as F

    print("Running install check (paddle_tpu)...")
    pt.seed(0)
    model = nn.Linear(2, 1)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    x = pt.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], "f4"))
    y = pt.to_tensor(np.array([[3.0], [7.0]], "f4"))

    loss = F.mse_loss(model(x), y)
    loss.backward()
    o.step()
    o.clear_grad()
    print(f"  eager step ok (loss={float(loss.numpy()):.4f}, "
          f"backend={jax.default_backend()})")

    def step(x, y):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[model], optimizers=[o])
    loss = cstep(x, y)
    print(f"  compiled step ok (loss={float(loss.numpy()):.4f})")

    n = jax.device_count()
    if n > 1:
        from paddle_tpu.parallel.fleet import Fleet
        fleet = Fleet().init(mesh_shape={"dp": n})
        dmodel = fleet.distributed_model(model)
        xs, ys = fleet.shard_batch(
            pt.to_tensor(np.tile(np.asarray(x.numpy()), (n, 1))),
            pt.to_tensor(np.tile(np.asarray(y.numpy()), (n, 1))))
        loss = F.mse_loss(dmodel(xs), ys)
        loss.backward()
        o.step()
        o.clear_grad()
        print(f"  data-parallel step ok on {n} devices "
              f"(loss={float(loss.numpy()):.4f})")
    print("Your paddle_tpu installation works. "
          "Models can be trained on this machine.")
    return True
