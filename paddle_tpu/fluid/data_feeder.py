"""fluid.DataFeeder + py_reader compat surface.

Rebuild of the reference feeding stack (reference:
python/paddle/fluid/data_feeder.py:212 DataFeeder — converts a minibatch
of python samples into the feed dict the Executor wants;
python/paddle/fluid/layers/io.py:553 py_reader / :831 double_buffer — a
queue the C++ executor pops from). On XLA the executor takes explicit
feeds, so PyReader keeps the queue in python and hands out feed dicts;
device-side double buffering is what io.DataLoader's prefetching core
already does (csrc/core.cpp), so double_buffer is the identity on an
already-prefetched reader.
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, convert_dtype
from ..static import StaticVar


class DataFeeder:
    """reference: data_feeder.py:212. feed_list entries are static data
    vars (or their names); `feed(minibatch)` returns {name: ndarray}."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def _names_dtypes(self):
        out = []
        for v in self.feed_vars:
            if isinstance(v, StaticVar):
                out.append((v.name, convert_dtype(v._dtype)))
            elif isinstance(v, Tensor):
                out.append((v.name, v.data.dtype))
            else:
                out.append((str(v), None))
        return out

    def feed(self, iterable):
        """minibatch: iterable of per-sample tuples (one entry per feed
        var) → {name: stacked ndarray}."""
        rows = list(iterable)
        if not rows:
            raise ValueError("empty minibatch")
        nd = self._names_dtypes()
        ncol = len(nd)
        cols = [[] for _ in range(ncol)]
        for row in rows:
            if len(row) != ncol:
                raise ValueError(
                    f"sample has {len(row)} fields, feed_list wants {ncol}")
            for c, v in enumerate(row):
                cols[c].append(np.asarray(v))
        out = {}
        for (name, dtype), col in zip(nd, cols):
            arr = np.stack(col)
            if dtype is not None:
                arr = arr.astype(dtype)
            out[name] = arr
        return out


class PyReader:
    """reference: fluid/reader.py:PyReader + layers/io.py:py_reader. The
    queue-into-the-executor design becomes: decorate a sample/batch
    generator, then iterate feed dicts (XLA wants explicit feeds)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        self.feeder = DataFeeder(feed_list or [])
        self.capacity = capacity
        self._batch_gen = None
        self._sample_gen = None
        self._started = False

    def decorate_sample_list_generator(self, generator, places=None):
        """generator() yields minibatches: lists of per-sample tuples."""
        self._sample_gen = generator
        return self

    decorate_paddle_reader = decorate_sample_list_generator

    def decorate_batch_generator(self, generator, places=None):
        """generator() yields ready feed tuples of batched arrays."""
        self._batch_gen = generator
        return self

    def start(self):
        self._started = True

    def reset(self):
        self._started = False

    def __iter__(self):
        names = [n for n, _ in self.feeder._names_dtypes()]
        if self._batch_gen is not None:
            for batch in self._batch_gen():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield dict(zip(names, batch))
        elif self._sample_gen is not None:
            for minibatch in self._sample_gen():
                yield self.feeder.feed(minibatch)
        else:
            raise ValueError("PyReader: decorate a generator first")


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """reference: layers/io.py:553. Creates the feed vars and a PyReader
    bound to them; `read_file(reader)` returns the vars."""
    from ..static import data as sdata
    import uuid
    prefix = name or ("py_reader_" + uuid.uuid4().hex[:6])
    feed_vars = [sdata(f"{prefix}_{i}", shape, dtype)
                 for i, (shape, dtype) in enumerate(zip(shapes, dtypes))]
    reader = PyReader(feed_list=feed_vars, capacity=capacity,
                      use_double_buffer=use_double_buffer)
    reader.vars = feed_vars
    return reader


def read_file(reader):
    """reference: layers/io.py:read_file — the data vars the reader
    feeds."""
    vars_ = getattr(reader, "vars", None)
    if vars_ is None:
        raise ValueError("read_file expects a py_reader(...) result")
    return vars_ if len(vars_) > 1 else vars_[0]


def double_buffer(reader, place=None, name=None):
    """reference: layers/io.py:831. Device-side prefetch is handled by the
    DataLoader's native prefetching core; identity here."""
    return reader


# --- reference data_feeder.py validator surface (commonly imported by
# ported user code: `from paddle.fluid.data_feeder import check_dtype`) ---

from ..tensor import convert_dtype  # noqa: F401,E402


def check_type(input, input_name, expected_type, op_name,
               extra_message=""):
    """reference data_feeder.py:check_type."""
    from ..tensor import Tensor
    if isinstance(expected_type, tuple):
        expected = expected_type
    else:
        expected = (expected_type,)
    # a Tensor satisfies any Variable-ish expectation
    if isinstance(input, Tensor):
        return
    if not isinstance(input, expected):
        raise TypeError(
            f"The type of '{input_name}' in {op_name} must be "
            f"{expected_type}, but received {type(input)}. {extra_message}")


def check_dtype(input_dtype, input_name, expected_dtype, op_name,
                extra_message=""):
    """reference data_feeder.py:check_dtype."""
    dt = str(input_dtype)
    if dt not in tuple(str(d) for d in expected_dtype):
        raise TypeError(
            f"The data type of '{input_name}' in {op_name} must be one of "
            f"{expected_dtype}, but received {dt}. {extra_message}")


def check_variable_and_dtype(input, input_name, expected_dtype, op_name,
                             extra_message=""):
    """reference data_feeder.py:check_variable_and_dtype."""
    from ..tensor import Tensor
    check_type(input, input_name, Tensor, op_name, extra_message)
    dtype = getattr(input, "dtype", None)
    if dtype is not None:
        import numpy as _np
        check_dtype(_np.dtype(dtype).name if not isinstance(dtype, str)
                    else dtype, input_name, expected_dtype, op_name,
                    extra_message)


class DataToLoDTensorConverter:
    """reference data_feeder.py:DataToLoDTensorConverter — padded-batch
    redesign: accumulates rows and converts to one array."""

    def __init__(self, place=None, lod_level=0, shape=None, dtype="float32"):
        self.shape = shape
        self.dtype = dtype
        self.data = []

    def feed(self, data):
        self.data.append(data)

    def done(self):
        import numpy as _np
        from ..tensor import Tensor
        return Tensor(_np.asarray(self.data, dtype=self.dtype))


class BatchedTensorProvider:
    """reference data_feeder.py:BatchedTensorProvider — generator-side
    batcher over feed_list shapes."""

    def __init__(self, feed_list, place=None, batch_size=1, generator=None,
                 drop_last=True):
        self.feed_list = feed_list
        self.batch_size = batch_size
        self.generator = generator
        self.drop_last = drop_last

    def __call__(self):
        import numpy as _np
        batch = []
        for item in self.generator():
            batch.append(item)
            if len(batch) == self.batch_size:
                yield [
                    _np.asarray([row[i] for row in batch])
                    for i in range(len(batch[0]))]
                batch = []
        if batch and not self.drop_last:
            yield [_np.asarray([row[i] for row in batch])
                   for i in range(len(batch[0]))]
