"""fluid.executor facade (reference: fluid/executor.py)."""
from ..static import Executor, global_scope, Scope  # noqa: F401


import contextlib
import numpy as _np


@contextlib.contextmanager
def scope_guard(scope):
    """reference executor.py:scope_guard — scopes are plain dicts here;
    the guard exists for ported code shape."""
    yield scope


def as_numpy(tensor):
    """reference executor.py:as_numpy."""
    if isinstance(tensor, (list, tuple)):
        return [as_numpy(t) for t in tensor]
    return tensor.numpy() if hasattr(tensor, "numpy") else _np.asarray(
        tensor)


class FetchHandler:
    """reference executor.py:FetchHandler — subclass and override
    handler(fetch_dict) for periodic fetches."""

    def __init__(self, var_dict=None, period_secs=60):
        self.var_dict = var_dict or {}
        self.period_secs = period_secs

    def handler(self, fetch_dict):
        raise NotImplementedError

    @staticmethod
    def help():
        return FetchHandler.__doc__


def dimension_is_compatible_with(first, second):
    """reference executor.py:dimension_is_compatible_with."""
    dim_len = len(first)
    if dim_len != len(second):
        return False
    for a, b in zip(first, second):
        if a is None or b is None or a < 0 or b < 0:
            continue
        if a != b:
            return False
    return True


def check_feed_shape_type(var, feed, num_places=1):
    """reference executor.py:check_feed_shape_type."""
    shape = getattr(var, "shape", None)
    if shape is not None and not dimension_is_compatible_with(
            tuple(feed.shape), tuple(shape)):
        raise ValueError(
            f"feed shape {tuple(feed.shape)} is not compatible with "
            f"declared shape {tuple(shape)}")
    return True


def dtype_is_compatible_with(first, second):
    """reference executor.py:dtype_is_compatible_with."""
    import numpy as _np
    try:
        return _np.dtype(str(first)) == _np.dtype(str(second))
    except TypeError:
        return str(first) == str(second)


def has_feed_operators(block=None, feed_targets=None, feed_holder_name=None):
    """reference executor.py — the jitted program feeds args directly."""
    return False


def has_fetch_operators(block=None, fetch_targets=None,
                        fetch_holder_name=None):
    return False
