"""fluid.annotations (reference: fluid/annotations.py)."""
import functools
import sys

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    """reference annotations.py:deprecated — marks an API deprecated,
    printing one warning per call site to stderr."""
    def decorator(func):
        err_msg = f"API {func.__name__} is deprecated since {since}. " \
                  f"Please use {instead} instead."
        if extra_message:
            full = err_msg + " " + extra_message
        else:
            full = err_msg

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            print(full, file=sys.stderr)
            return func(*args, **kwargs)

        wrapper.__doc__ = (full + "\n\n") + (func.__doc__ or "")
        return wrapper
    return decorator
