"""fluid.evaluator (reference: python/paddle/fluid/evaluator.py:1 — the
fluid-era Evaluator family, deprecated upstream in favor of
fluid.metrics; kept for API parity).

The reference Evaluators maintain accumulator VARIABLES inside the
Program and emit update ops each step. The rebuild keeps accumulation on
the host (the numbers involved are a handful of scalars; device round
trips would cost more than they save) and delegates the math to
paddle_tpu.metric, which is the maintained implementation."""
from __future__ import annotations

import warnings

import numpy as np

from .. import metric as _metric

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """reference: evaluator.py:45 — base: states + reset/eval."""

    def __init__(self, name=None, **kwargs):
        warnings.warn("fluid.evaluator.* is the deprecated fluid-era API;"
                      " prefer paddle_tpu.metric", DeprecationWarning,
                      stacklevel=2)
        self.name = name
        self.states = []

    def reset(self, executor=None, reset_program=None):
        self._m.reset()

    def eval(self, executor=None, eval_program=None):
        return self._m.accumulate()


class ChunkEvaluator(Evaluator):
    """reference: evaluator.py:127 — chunking F1 from per-batch counts.
    update(num_infer_chunks, num_label_chunks, num_correct_chunks)."""

    def __init__(self, input=None, label=None, chunk_scheme=None,
                 num_chunk_types=None, excluded_chunk_types=None,
                 name=None):
        super().__init__(name)
        self._m = _metric.ChunkEvaluator()

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self._m.update(num_infer_chunks, num_label_chunks,
                       num_correct_chunks)
        return self._m.accumulate()


class EditDistance(Evaluator):
    """reference: evaluator.py:218 — accumulates PRECOMPUTED per-instance
    distances (the reference wires an edit_distance op in front); returns
    (avg distance, instance error rate)."""

    def __init__(self, input=None, label=None, ignored_tokens=None,
                 name=None):
        super().__init__(name)
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._total = 0.0
        self._seq_num = 0
        self._errors = 0

    def update(self, distances, seq_num=None):
        distances = np.asarray(distances, "f4").reshape(-1)
        self._total += float(distances.sum())
        self._seq_num += int(seq_num if seq_num is not None
                             else len(distances))
        self._errors += int((distances > 0).sum())
        return self.eval()

    def eval(self, executor=None, eval_program=None):
        if not self._seq_num:
            return 0.0, 0.0
        return (self._total / self._seq_num,
                self._errors / self._seq_num)


class DetectionMAP(Evaluator):
    """reference: evaluator.py:299 — detection mean average precision."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral", name=None):
        super().__init__(name)
        self._m = _metric.DetectionMAP(
            class_num=class_num, overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version,
            background_label=background_label)

    def update(self, *args, **kwargs):
        self._m.update(*args, **kwargs)
        return self._m.accumulate()

    def get_map_var(self):
        return None  # no Program variable in the rebuilt design
