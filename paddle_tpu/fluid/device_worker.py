"""fluid.device_worker facade (reference: fluid/device_worker.py) —
the worker-desc generator classes live with TrainerDesc in
trainer_desc.py here (one module owns the trainer/worker pairing)."""
from .trainer_desc import (DeviceWorker, Hogwild, DownpourSGD,  # noqa
                           DownpourSGDOPT, Section)

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "DownpourSGDOPT",
           "Section"]
