"""fluid.initializer — importable-module facade over paddle_tpu.initializer
(reference: python/paddle/fluid/initializer.py)."""
from ..initializer import *  # noqa: F401,F403
from ..initializer import (Initializer, Constant, Uniform, Normal,  # noqa
                           TruncatedNormal, Xavier, XavierUniform,
                           XavierNormal, MSRA, KaimingUniform,
                           KaimingNormal, Bilinear, NumpyArrayInitializer)
