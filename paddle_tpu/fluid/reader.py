"""fluid.reader (reference: fluid/reader.py) — PyReader and DataLoader
entry points. The real implementations live in fluid.data_feeder
(PyReader: queue + feed dicts) and paddle_tpu.io (DataLoader: the
prefetching loader over the C++ native batcher)."""
from .data_feeder import PyReader  # noqa: F401
from ..io import DataLoader  # noqa: F401

__all__ = ["PyReader", "DataLoader"]
