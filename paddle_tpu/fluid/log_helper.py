"""fluid.log_helper (reference: fluid/log_helper.py)."""
import logging

__all__ = ["get_logger"]


def get_logger(name, level, fmt=None):
    """reference log_helper.py:get_logger — named logger with its own
    stream handler (does not propagate to root, so repeated calls don't
    duplicate lines)."""
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler()
        if fmt:
            handler.setFormatter(logging.Formatter(fmt=fmt))
        logger.addHandler(handler)
    logger.propagate = False
    return logger
