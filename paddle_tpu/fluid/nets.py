"""fluid.nets — the book-example composite blocks.

Rebuild of the reference's nets.py (reference: python/paddle/fluid/nets.py
— simple_img_conv_pool:29, img_conv_group:139, sequence_conv_pool:252,
glu:320, scaled_dot_product_attention:362). These compose the fluid-compat
param-creating layers (fluid/layers.py) exactly the way the reference
composes its LayerHelper ops, so book examples port with an import swap.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..ops import nn_ops as F
from . import layers as FL

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """reference: nets.py:29 — conv2d then pool2d."""
    conv_out = FL.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, stride=conv_stride,
                         padding=conv_padding, dilation=conv_dilation,
                         groups=conv_groups, param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    return FL.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                     pool_stride=pool_stride, pool_padding=pool_padding,
                     global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """reference: nets.py:139 — the VGG block: N convs (+BN +dropout)
    then one pool."""
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]
    n = len(conv_num_filter)

    def per(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    padding = per(conv_padding)
    fsize = per(conv_filter_size)
    with_bn = per(conv_with_batchnorm)
    drop = per(conv_batchnorm_drop_rate)
    pattr = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * n

    for i in range(n):
        act = conv_act if not with_bn[i] else None
        tmp = FL.conv2d(tmp, num_filters=conv_num_filter[i],
                        filter_size=fsize[i], padding=padding[i],
                        param_attr=pattr[i], act=act)
        if with_bn[i]:
            tmp = FL.batch_norm(tmp, act=conv_act)
            if drop[i] > 0:
                tmp = F.dropout(tmp, p=drop[i])
    return FL.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                     pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       lengths=None):
    """reference: nets.py:252 — sequence_conv then sequence_pool. Input is
    the padded (B, T, D) formulation; `lengths` masks padding."""
    conv = FL.sequence_conv(input, num_filters=num_filters,
                            filter_size=filter_size, param_attr=param_attr,
                            bias_attr=bias_attr, act=act, length=lengths)
    from ..ops.sequence import sequence_pool
    return sequence_pool(conv, pool_type=pool_type, length=lengths)


def glu(input, dim=-1):
    """reference: nets.py:320 — split in half on `dim`; a * sigmoid(b)."""
    a, b = ops.split(input, 2, axis=dim)
    return a * ops.sigmoid(b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """reference: nets.py:362 — multi-head attention over (B, S, D)
    q/k/v; returns (B, Sq, D_v)."""
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")
    b = queries.shape[0]

    def split_heads(x):
        s, d = x.shape[1], x.shape[2]
        return x.reshape([b, s, num_heads, d // num_heads]).transpose(
            [0, 2, 1, 3])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    ctx = F.scaled_dot_product_attention(q, k, v, dropout_p=dropout_rate,
                                         training=dropout_rate > 0)
    s = ctx.shape[2]
    return ctx.transpose([0, 2, 1, 3]).reshape([b, s, -1])
