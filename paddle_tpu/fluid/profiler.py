"""fluid.profiler facade (reference: fluid/profiler.py)."""
import contextlib

from ..utils.profiler import (profiler, start_profiler,  # noqa: F401
                              stop_profiler, reset_profiler, print_stats)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """reference profiler.py:cuda_profiler — drives nvprof via the CUDA
    runtime, which has no TPU analogue. Kept as an explicit error so
    ported code fails with direction instead of AttributeError."""
    raise RuntimeError(
        "cuda_profiler drives nvprof (CUDA-only). Use "
        "fluid.profiler.profiler(...) or "
        "paddle_tpu.utils.profiler.start_profiler for the XLA trace "
        "profiler, and summarize_trace for per-op device time.")
    yield  # pragma: no cover
