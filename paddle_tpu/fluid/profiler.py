"""fluid.profiler facade (reference: fluid/profiler.py)."""
from ..utils.profiler import (profiler, start_profiler,  # noqa: F401
                              stop_profiler, reset_profiler, print_stats)
