"""fluid.param_attr facade (reference: fluid/param_attr.py)."""
from ..param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
