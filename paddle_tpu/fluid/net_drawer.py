"""fluid.net_drawer (reference: python/paddle/fluid/net_drawer.py —
Graphviz op-graph drawing CLI). Thin front over debugger.program_to_dot
for the rebuilt Program."""
from __future__ import annotations

from .debugger import program_to_dot

__all__ = ["draw_graph"]


def draw_graph(startup_program=None, main_program=None, graph_name="graph",
               path=None, **_):
    """Write main_program's op graph as DOT (reference keeps startup and
    main separate; startup in this rebuild is parameter placement, which
    has no op graph)."""
    from .. import static
    program = main_program or static.default_main_program()
    dot = program_to_dot(program, graph_name=graph_name)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
