"""fluid.clip facade (reference: fluid/clip.py)."""
from ..clip import *  # noqa: F401,F403


# --- reference clip.py internals --------------------------------------------
from ..clip import (GradientClipByValue, GradientClipByNorm,  # noqa: F401
                    GradientClipByGlobalNorm)
from ..clip import ClipGradBase as GradientClipBase  # noqa: F401


class BaseErrorClipAttr:
    """reference clip.py:BaseErrorClipAttr."""

    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


# ErrorClipByValue comes from ..clip via the star import above (the tape
# applies it to a var's incoming gradient); BaseErrorClipAttr is its
# reference-parity base.

def error_clip_callback(block=None, context=None):
    """reference clip.py:error_clip_callback — grad-op callback hook; the
    jax.grad engine has no per-op callback, clipping applies via
    optimizer grad_clip instead."""


def append_gradient_clip_ops(param_grads):
    """reference clip.py:append_gradient_clip_ops — functional redesign:
    params sharing one .gradient_clip_attr are clipped as a GROUP (one
    joint call), preserving GradientClipByGlobalNorm's combined-norm
    semantics; returns the new (param, grad) list in input order."""
    groups = {}          # id(attr) -> (attr, [index])
    out = [(p, g) for p, g in param_grads]
    for i, (p, g) in enumerate(param_grads):
        attr = getattr(p, "gradient_clip_attr", None)
        if attr is not None and g is not None:
            groups.setdefault(id(attr), (attr, []))[1].append(i)
    for attr, idxs in groups.values():
        clipped = attr([param_grads[i] for i in idxs])
        for i, pg in zip(idxs, clipped):
            out[i] = pg
    return out
