"""paddle_tpu.fluid — reference-API compatibility namespace.

Mirrors the `paddle.fluid` surface of the reference (python/paddle/fluid/
__init__.py) so code written against it ports with an import swap:
Program/Executor/program_guard, fluid.data, fluid.layers.*, fluid.dygraph.*,
optimizer/initializer/regularizer/clip/metrics, CPUPlace/CUDAPlace.

The implementations are the TPU-native ones — this module only re-shapes
the API.
"""
from __future__ import annotations

from ..static import (Program, Executor, program_guard, data,
                      default_main_program, default_startup_program,
                      CompiledProgram, ParallelExecutor, BuildStrategy,
                      ExecutionStrategy, global_scope, name_scope,
                      append_backward)
from ..device import CPUPlace, CUDAPlace, TPUPlace
from ..param_attr import ParamAttr, WeightNormParamAttr
# importable-module facades (so `import paddle_tpu.fluid.initializer` and
# friends work like `import paddle.fluid.initializer` in the reference)
from . import initializer
from . import regularizer
from . import clip
from . import optimizer
from . import metrics
from . import io
from . import framework
from . import executor
from . import backward
from . import unique_name
from . import profiler as profiler  # noqa: F401
from ..tensor import Tensor
from ..static import enable_static, disable_static
from . import layers
from . import dygraph
from . import nets
from . import contrib
from . import transpiler
from .transpiler import (DistributeTranspiler,  # noqa: F401
                         DistributeTranspilerConfig, memory_optimize,
                         release_memory)
from .data_feeder import DataFeeder, PyReader
from . import incubate
from . import install_check
from . import debugger
from . import net_drawer
from . import evaluator
from . import trainer_desc
from . import data_feed_desc
from .trainer_desc import (TrainerDesc, MultiTrainer,  # noqa: F401
                           DistMultiTrainer, TrainerFactory, Communicator)
from .data_feed_desc import DataFeedDesc  # noqa: F401
communicator = trainer_desc  # Communicator shares trainer_desc's module
from . import device_worker  # noqa: E402 (facade over trainer_desc)
from . import trainer_factory  # noqa: E402 (adds FetchHandler pair)
from . import annotations  # noqa: E402
from . import average  # noqa: E402
from . import dataset  # noqa: E402
from . import default_scope_funcs  # noqa: E402
from . import input  # noqa: E402
from . import lod_tensor  # noqa: E402
from . import log_helper  # noqa: E402
from . import reader  # noqa: E402
from . import wrapped_decorator  # noqa: E402
from . import learning_rate_decay  # noqa: E402
from .input import one_hot, embedding  # noqa: F401,E402
from .dygraph import enable_dygraph, disable_dygraph  # noqa: F401,E402
from .lod_tensor import (_LoDTensor as LoDTensor,  # noqa: F401,E402
                         create_lod_tensor, create_random_int_lodtensor)
from ..ops.imperative_flow import (  # noqa: F401,E402
    TensorArray as LoDTensorArray)
from ..device import CUDAPinnedPlace  # noqa: F401,E402
from ..static import Scope  # noqa: F401,E402
from .io import save, load  # noqa: F401,E402
from .dataset import DatasetFactory  # noqa: F401,E402

VarBase = Tensor  # the dygraph-era C++ tensor class name


class Variable(Tensor):
    """Alias for parity with fluid.framework.Variable."""


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield scope
    return guard()


def memory_optimize(program=None, **kw):
    """reference: transpiler memory_optimize — XLA buffer assignment +
    donation already performs this; no-op kept for parity."""


def release_memory(program=None, **kw):
    pass


from .framework import set_flags, get_flags  # noqa: F401,E402


def is_compiled_with_cuda():
    from ..device import is_compiled_with_cuda as f
    return f()


def cuda_places(device_ids=None):
    import jax
    devs = jax.devices()
    ids = device_ids if device_ids is not None else range(len(devs))
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=1):
    return [CPUPlace() for _ in range(device_count)]
