"""fluid.dygraph learning-rate decay classes.

Reference: python/paddle/fluid/dygraph/learning_rate_scheduler.py. These
are the 1.x dygraph-era schedules: the object is passed as
``learning_rate=`` to an optimizer, and each optimizer step CALLS it —
computing the lr at the current ``step_num`` and then advancing
``step_num`` by ``step_size``. They differ from ``optimizer.lr``'s 2.x
``LRScheduler`` protocol (user-driven ``scheduler.step()`` per epoch),
so they are distinct classes, not aliases.

TPU-first redesign: the schedule is host-side float math (the reference
built one-op LR sub-graphs returning Variables). The optimizer refreshes
its device-resident lr tensor from the float before each update, so
compiled steps still read lr as input state — no retrace per decay step.
"""
import math

__all__ = [
    "NoamDecay", "PiecewiseDecay", "NaturalExpDecay", "ExponentialDecay",
    "InverseTimeDecay", "PolynomialDecay", "CosineDecay", "LinearLrWarmup",
]


class LearningRateDecay:
    """Base class (reference learning_rate_scheduler.py:LearningRateDecay):
    __call__ = compute lr at step_num, then advance."""

    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def peek(self):
        """lr at the CURRENT step_num without mutating ANY schedule
        state. step() is already pure for every built-in decay except
        LinearLrWarmup (whose step() advances a wrapped inner decay —
        it overrides this); the optimizer uses peek() for its init-time
        get_lr() value."""
        return float(self.step())

    def create_lr_var(self, lr):
        # The reference materialized a [1] Variable; host float math
        # keeps the schedule out of the compiled graph here.
        return float(lr)

    def step(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:PiecewiseDecay."""

    def __init__(self, boundaries, values, begin, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:NaturalExpDecay."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * math.exp(-self.decay_rate * div)


class ExponentialDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:ExponentialDecay."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * (self.decay_rate ** div)


class InverseTimeDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:InverseTimeDecay."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate / (1 + self.decay_rate * div)


class PolynomialDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:PolynomialDecay (incl. the
    cycle branch's div_res=1 special case at step 0)."""

    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        step_num = self.step_num
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step_num / float(decay_steps))
            if step_num == 0:
                div = 1.0
            decay_steps = decay_steps * div
        else:
            step_num = min(step_num, decay_steps)
        return ((self.learning_rate - self.end_learning_rate) *
                (1 - step_num / decay_steps) ** self.power +
                self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:CosineDecay — epoch-granular:
    lr = base * 0.5 * (cos(cur_epoch*pi/epochs) + 1) with
    cur_epoch = floor(step_num / step_each_epoch). NOT the same curve as
    optimizer.lr.CosineAnnealingDecay (continuous T_max schedule)."""

    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return self.learning_rate * 0.5 * (
            math.cos(cur_epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    """reference learning_rate_scheduler.py:NoamDecay."""

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32", learning_rate=1.0):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        a = self.step_num ** -0.5
        b = (self.warmup_steps ** -1.5) * self.step_num
        return self.learning_rate * (self.d_model ** -0.5) * min(a, b)


class LinearLrWarmup(LearningRateDecay):
    """reference learning_rate_scheduler.py:LinearLrWarmup. Matches the
    reference CODE during warmup (lr = ratio * step_num, i.e. a ramp
    from ~0 — its docstring's `start_lr +` term is not in its code);
    after warmup returns the wrapped schedule/float."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        if not isinstance(learning_rate, (int, float, LearningRateDecay)):
            raise TypeError(
                "the type of learning_rate should be [int, float or "
                "LearningRateDecay], the current type is "
                f"{type(learning_rate)}")
        self.learning_rate = learning_rate
        self.warmup_steps = warmup_steps
        if not end_lr > start_lr:
            raise AssertionError(
                f"end_lr {end_lr} must be greater than start_lr {start_lr}")
        self.lr_ratio_before_warmup = (
            float(end_lr) - float(start_lr)) / float(warmup_steps)

    def step(self):
        base_lr = self.learning_rate
        if isinstance(self.learning_rate, LearningRateDecay):
            base_lr = base_lr()
        if self.step_num < self.warmup_steps:
            return self.lr_ratio_before_warmup * self.step_num
        return base_lr

    def peek(self):
        # step() advances the wrapped inner schedule via base_lr() —
        # peek the inner decay instead so an init-time read (the
        # optimizer's get_lr() seed) leaves its step_num untouched.
        if self.step_num < self.warmup_steps:
            return float(self.lr_ratio_before_warmup * self.step_num)
        base_lr = self.learning_rate
        if isinstance(base_lr, LearningRateDecay):
            return base_lr.peek()
        return float(base_lr)
