"""fluid.input (reference: fluid/input.py) — the 1.6-era non-LoD
one_hot/embedding entry points (same kernels as fluid.layers, new-style
argument names)."""
__all__ = ["one_hot", "embedding"]


def one_hot(input, depth, allow_out_of_range=False):
    """reference input.py:one_hot — ids → one-hot along a NEW last axis.
    With allow_out_of_range, out-of-range ids produce all-zero rows
    (jax's one_hot semantics natively); otherwise they are a user error
    the reference checks at runtime. In eager mode the ids are concrete,
    so we match the reference and raise; under jit/static tracing XLA
    cannot raise at run time, so out-of-range ids keep producing zero
    rows rather than UB."""
    from ..ops.manip import one_hot as _one_hot
    if not allow_out_of_range:
        from .. import dispatch
        import jax as _jax
        data = getattr(input, "data", input)
        if (not dispatch.in_static_mode() and data is not None
                and not isinstance(data, _jax.core.Tracer)):
            import numpy as _np
            ids = _np.asarray(_jax.device_get(data))
            if ids.size and (ids.min() < 0 or ids.max() >= depth):
                bad = int(ids.min()) if ids.min() < 0 else int(ids.max())
                raise ValueError(
                    f"one_hot: input id {bad} is out of range for "
                    f"depth {depth} (expected 0 <= id < depth); pass "
                    "allow_out_of_range=True for zero-row semantics")
    out = _one_hot(input, depth)
    # The reference appends depth after the trailing [..., 1] axis is
    # squeezed; manip.one_hot already matches that contract.
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference input.py:embedding (v2 signature; the layers.embedding
    twin keeps the LoD-era contract). is_sparse/is_distributed are
    storage strategies of the reference's PS path — lookup semantics are
    identical here (sharded storage is parallel/embedding.py's job)."""
    from .layers import embedding as _embedding
    return _embedding(input, size, is_sparse=is_sparse,
                      padding_idx=padding_idx, param_attr=param_attr,
                      dtype=dtype)
