"""paddle_tpu.fluid.layers — the fluid.layers functional surface.

Mirrors reference python/paddle/fluid/layers/{nn,tensor,ops,loss,
control_flow}.py. Param-creating functions (fc, conv2d, batch_norm,
embedding, ...) follow the reference's LayerHelper pattern: parameters are
created on first call and recorded into the active static Program (these
are primarily for static-graph code; dygraph code uses paddle_tpu.nn Layer
classes, as in the reference).
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, Parameter, convert_dtype
from .. import ops
from ..ops import nn_ops as F
from ..ops import loss as L
from .. import initializer as I
from ..param_attr import ParamAttr
from ..static import data  # noqa: F401 (fluid.layers.data parity)
from . import layer_function_generator  # noqa: F401
from .layer_function_generator import (generate_layer_fn,  # noqa: F401
                                       generate_activation_fn, autodoc,
                                       templatedoc)
from ..ops.control_flow import cond, while_loop, case, switch_case  # noqa
from ..ops.imperative_flow import (IfElse, Switch, DynamicRNN,  # noqa: F401
                                   TensorArray, create_array, array_write,
                                   array_read, array_length)
from .. import metric as _metric

# re-export the whole functional op surface
from ..ops.math import *  # noqa: F401,F403
from ..ops.manip import *  # noqa: F401,F403
from ..ops.creation import *  # noqa: F401,F403
from ..ops.nn_ops import *  # noqa: F401,F403
from ..ops.sequence import (sequence_pool, sequence_softmax,  # noqa: F401
                            sequence_reverse, sequence_expand,
                            sequence_pad, sequence_unpad, sequence_concat,
                            sequence_conv, sequence_slice,
                            sequence_expand_as, sequence_reshape,
                            sequence_scatter, sequence_enumerate,
                            sequence_first_step, sequence_last_step)
from ..ops.crf import linear_chain_crf, crf_decoding  # noqa: F401
from ..ops.ctc import warpctc, ctc_greedy_decoder  # noqa: F401
from ..distribution import (Distribution, Uniform, Normal,  # noqa: F401
                            Categorical, MultivariateNormalDiag)
from .layers_rnn import (RNNCell, LSTMCell, GRUCell, Decoder,  # noqa: F401
                         DecodeHelper, SampleEmbeddingHelper,
                         dynamic_lstm, dynamic_lstmp, dynamic_gru,
                         gru_unit, lstm_unit, lstm, rnn, beam_search,
                         beam_search_decode)
from .data_feeder import py_reader, read_file, double_buffer  # noqa: F401
from ..ops.detection import (iou_similarity, box_coder,  # noqa: F401
                             box_clip, prior_box, density_prior_box,
                             anchor_generator, yolo_box, yolov3_loss,
                             sigmoid_focal_loss, bipartite_match,
                             target_assign, ssd_loss, multiclass_nms,
                             detection_output, polygon_box_transform,
                             roi_align, roi_pool, generate_proposals,
                             distribute_fpn_proposals,
                             collect_fpn_proposals, multi_box_head)
from ..nn.decode import (BeamSearchDecoder, dynamic_decode,  # noqa: F401
                         gather_tree, TrainingHelper,
                         GreedyEmbeddingHelper, SamplingEmbeddingHelper,
                         BasicDecoder)
from ..ops.loss import (softmax_with_cross_entropy,  # noqa: F401
                        sigmoid_cross_entropy_with_logits,
                        square_error_cost, huber_loss, kl_div, log_loss,
                        rank_loss, margin_ranking_loss, bpr_loss,
                        hinge_loss, smooth_l1_loss)

reduce_sum = ops.sum
reduce_mean = ops.mean
reduce_max = ops.max
reduce_min = ops.min
reduce_prod = ops.prod
elementwise_add = ops.add
elementwise_sub = ops.subtract
elementwise_mul = ops.multiply
elementwise_div = ops.divide
fill_constant = ops.full


def _act(x, act):
    if act is None:
        return x
    return getattr(F, act)(x)


def _param(attr, shape, dtype, default_init, is_bias=False):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = None
    if isinstance(attr, ParamAttr) and isinstance(attr.initializer,
                                                  I.Initializer):
        init = attr.initializer
    init = init or default_init
    p = Parameter(init(shape, convert_dtype(dtype)),
                  name=attr.name if isinstance(attr, ParamAttr) else None)
    if isinstance(attr, ParamAttr):
        p.regularizer = attr.regularizer
        if not attr.trainable:
            p.stop_gradient = True
            p.trainable = False
    return p


def sequence_conv(input, num_filters, filter_size=3, padding_start=None,
                  param_attr=None, bias_attr=None, act=None, length=None,
                  name=None):
    """reference: layers/sequence_lod.py:sequence_conv (the LayerHelper,
    param-creating form; the functional op is ops.sequence.sequence_conv).
    Shadows the functional re-export above on purpose."""
    from ..ops.sequence import sequence_conv as _seq_conv_op
    d = input.shape[-1]
    w = _param(param_attr, (filter_size * d, num_filters), "float32",
               I.XavierUniform())
    b = _param(bias_attr, (num_filters,), "float32", I.Constant(0.0),
               is_bias=True)
    out = _seq_conv_op(input, w, b, filter_size=filter_size,
                       padding_start=padding_start, length=length)
    return _act(out, act)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """reference: layers/nn.py:fc."""
    in_dim = int(np.prod(input.shape[num_flatten_dims:]))
    w = _param(param_attr, (in_dim, size), "float32", I.XavierUniform())
    b = _param(bias_attr, (size,), "float32", I.Constant(0.0), is_bias=True)
    lead = tuple(-1 if (d is None or d < 0) else d
                 for d in input.shape[:num_flatten_dims])
    x = input if len(input.shape) == num_flatten_dims + 1 else ops.reshape(
        input, lead + (in_dim,))
    out = F.linear(x, w, b)
    return _act(out, act)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """reference: layers/nn.py:embedding."""
    w = _param(param_attr, tuple(size), dtype,
               I.Normal(0.0, 1.0 / np.sqrt(size[1])))
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    """reference: layers/nn.py:conv2d."""
    ks = F._pair(filter_size, 2)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fan_in = cin * ks[0] * ks[1] // groups
    w = _param(param_attr, (num_filters, cin // groups, ks[0], ks[1]),
               "float32", I.Normal(0.0, float(np.sqrt(2.0 / fan_in))))
    b = _param(bias_attr, (num_filters,), "float32", I.Constant(0.0),
               is_bias=True)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


_bn_counter = [0]
_bn_stats = {}


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None):
    """reference: layers/nn.py:batch_norm. Running stats are persistable
    Tensors registered with the program's param store (non-trainable)."""
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    w = _param(param_attr, (c,), "float32", I.Constant(1.0))
    b = _param(bias_attr, (c,), "float32", I.Constant(0.0), is_bias=True)
    _bn_counter[0] += 1
    key = name or f"bn_{_bn_counter[0]}"
    if key not in _bn_stats:
        import jax.numpy as jnp
        rm = Parameter(jnp.zeros((c,)), name=key + "_mean", trainable=False)
        rv = Parameter(jnp.ones((c,)), name=key + "_var", trainable=False)
        _bn_stats[key] = (rm, rv)
    rm, rv = _bn_stats[key]
    out, new_rm, new_rv = F.batch_norm(
        input, rm, rv, w, b, training=not is_test, momentum=momentum,
        epsilon=epsilon, data_format=data_layout)
    if not is_test and not hasattr(out, "program"):
        rm.data, rv.data = new_rm.data, new_rv.data
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: layers/nn.py:layer_norm."""
    shape = tuple(input.shape[begin_norm_axis:])
    w = _param(param_attr, shape, "float32", I.Constant(1.0)) if scale \
        else None
    b = _param(bias_attr, shape, "float32", I.Constant(0.0), is_bias=True) \
        if shift else None
    out = F.layer_norm(input, shape, w, b, epsilon)
    return _act(out, act)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """reference: layers/loss.py:cross_entropy — input is PROBABILITIES
    (post-softmax), per the fluid-era semantics."""
    return L.cross_entropy(input, label, soft_label=soft_label,
                           ignore_index=ignore_index, use_softmax=False,
                           reduction="none")


def expand(x, expand_times, name=None):
    """reference layers/nn.py:expand — fluid-era semantics: TILE each dim
    by expand_times (the 2.x `paddle.expand` broadcast-to-shape op is
    ops.manip.expand; this facade shadows the star-import with the
    fluid behavior ported code expects)."""
    return ops.tile(x, expand_times)


def cross_entropy2(input, label, ignore_index=-100):
    """reference: layers/loss.py:263 cross_entropy2 — same hard-label CE
    over probabilities as cross_entropy, the op variant that also matched
    x's shape (the extra outputs were an implementation detail)."""
    return L.cross_entropy(input, label, soft_label=False,
                           ignore_index=ignore_index, use_softmax=False,
                           reduction="none")


def mean(x, name=None):
    return ops.mean(x)


def accuracy(input, label, k=1):
    """reference: layers/metric_op.py:accuracy (works eagerly and in
    static graphs via the op path)."""
    from ..ops.math import accuracy_top1
    if k == 1:
        return accuracy_top1(input, label)
    def impl(pred, lbl):
        import jax.numpy as jnp
        import jax
        topk_idx = jax.lax.top_k(pred, k)[1]
        return jnp.mean(jnp.any(
            topk_idx == lbl.reshape(-1, 1), axis=-1).astype(jnp.float32))
    from ..dispatch import apply
    return apply(impl, (input, label), nondiff=True, name="accuracy")


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, data_format="NCHW",
           name=None):
    return F.pool2d(input, pool_size, pool_type, pool_stride, pool_padding,
                    global_pooling, data_format)


def dropout(x, dropout_prob, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    """reference: layers/nn.py:dropout."""
    mode = ("upscale_in_train"
            if dropout_implementation == "upscale_in_train"
            else "downscale_in_infer")
    return F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def sequence_mask(x, maxlen=None, dtype="int64"):
    """reference: sequence_mask op — [B] lengths -> [B, maxlen] mask.
    maxlen=None derives it from the data, which requires a concrete tensor
    (XLA needs static shapes): under jit/static tracing pass maxlen."""
    from ..dispatch import apply
    import jax
    import jax.numpy as jnp
    dt = convert_dtype(dtype)
    if maxlen is None:
        from ..tensor import as_tensor
        data = as_tensor(x).data
        if data is None or isinstance(data, jax.core.Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) needs a concrete lengths "
                "tensor; pass an explicit maxlen under jit/static mode "
                "(output shape must be static on TPU)")
        maxlen = int(np.asarray(jax.device_get(data)).max())

    def impl(lengths, maxlen):
        rng = jnp.arange(maxlen)
        return (rng[None, :] < lengths[:, None]).astype(dt)
    return apply(impl, (x,), dict(maxlen=maxlen), nondiff=True,
                 name="sequence_mask")


def softmax(input, axis=-1, name=None):
    return F.softmax(input, axis=axis)


def relu(x, name=None):
    return F.relu(x)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    return ops.matmul(x, y, transpose_x, transpose_y, alpha)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference: mul_op — flatten then matmul."""
    xf = ops.reshape(x, (int(np.prod(x.shape[:x_num_col_dims])), -1))
    yf = ops.reshape(y, (int(np.prod(y.shape[:y_num_col_dims])), -1))
    return ops.matmul(xf, yf)


# ---------------------------------------------------------------------------
# parameter-server-era ops (reference: layers/io.py Send/Recv/ListenAndServ)
# — the PS architecture is redesigned away on TPU (SURVEY §2 row 22:
# sharded embeddings + collectives), so these raise with a pointer instead
# of silently doing nothing.

def _ps_stub(name):
    def f(*a, **kw):
        raise RuntimeError(
            f"fluid.layers.{name} is the parameter-server-mode op "
            "(reference layers/io.py); the TPU redesign replaces the PS "
            "architecture with sharded embeddings + ICI collectives — "
            "see paddle_tpu.parallel.embedding and parallel.fleet")
    f.__name__ = name
    return f


Send = _ps_stub("Send")
Recv = _ps_stub("Recv")
ListenAndServ = _ps_stub("ListenAndServ")
BlockGuardServ = _ps_stub("BlockGuardServ")


def monkey_patch_reader_methods(reader):
    """reference layers/io.py:monkey_patch_reader_methods — the reader
    variable already exposes its methods here; identity for parity."""
    return reader


# ---------------------------------------------------------------------------
# parity tail: the remaining reference layer surface
from .layers_extra import *  # noqa: F401,F403,E402
from .layers_extra2 import *  # noqa: F401,F403,E402
from ..utils.debug import Print, Assert  # noqa: F401,E402
from ..nn.rnn import StaticRNN  # noqa: F401,E402
from ..ops.imperative_flow import While  # noqa: F401,E402


# ---------------------------------------------------------------------------
# py_func (reference: layers/nn.py py_func + PyFuncRegistry) — TPU-native
# redesign over jax.pure_callback: the python callable runs on the host at
# execution time, inside jit, with results shipped back to the device.

class PyFuncRegistry:
    """reference layers/nn.py:PyFuncRegistry."""

    _registry = []

    def __init__(self, func):
        self.func = func
        self.id = len(PyFuncRegistry._registry)
        PyFuncRegistry._registry.append(self)

    @classmethod
    def registered_func(cls, i):
        return cls._registry[i].func

    @classmethod
    def registered_func_num(cls):
        return len(cls._registry)


# py_func itself lives in layers_extra.py (pure_callback with custom-VJP
# backward support); PyFuncRegistry here completes the reference pair.


def save(x, file_path, overwrite=True):
    """reference layers/tensor.py:save — single-var save op."""
    import os as _os
    import numpy as _np
    target = file_path if file_path.endswith(".npy") else file_path + ".npy"
    if not overwrite and _os.path.exists(target):
        raise RuntimeError(f"{target} exists and overwrite=False")
    _np.save(target, x.numpy())


def save_combine(x, file_path, overwrite=True):
    """reference layers/tensor.py:save_combine — many vars, one file."""
    from .. import io as _io
    _io.save({getattr(v, "name", f"var_{i}") or f"var_{i}": v
              for i, v in enumerate(x)}, file_path)


def load_combine(out, file_path):
    """reference layers/tensor.py:load_combine."""
    from .. import io as _io
    state = _io.load(file_path)
    vals = list(state.values())
    for v, val in zip(out, vals):
        v.set_value(val)
    return out


# ---------------------------------------------------------------------------
# LoD machinery internals (reference: layers/control_flow.py) — the padded
# redesign has no LoD rank tables; block guards exist as working no-op
# context managers for ported `with` blocks, converters raise with the
# padded-equivalent pointer.

class BlockGuard:
    """reference control_flow.py:BlockGuard — with-block scoping is
    python-native here."""

    def __init__(self, main_program=None):
        self.main_program = main_program

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class BlockGuardWithCompletion(BlockGuard):
    def __init__(self, rnn=None):
        super().__init__()
        self.rnn = rnn


class WhileGuard(BlockGuard):
    def __init__(self, while_op=None):
        super().__init__()
        self.while_op = while_op


class ConditionalBlockGuard(BlockGuard):
    def __init__(self, block=None):
        super().__init__()
        self.block = block


class IfElseBlockGuard(BlockGuard):
    def __init__(self, is_true=True, ifelse=None):
        super().__init__()
        self.is_true = is_true


class ConditionalBlock:
    """reference control_flow.py:ConditionalBlock — use layers.cond /
    layers.IfElse; kept for construction parity of ported graph builders."""

    def __init__(self, inputs=None, is_scalar_condition=False, name=None):
        self.inputs = inputs or []
        self.is_scalar_condition = is_scalar_condition

    def block(self):
        return ConditionalBlockGuard(self)

    def complete(self):
        pass


class StaticRNNMemoryLink:
    """reference control_flow.py:StaticRNNMemoryLink record."""

    def __init__(self, init, pre_mem, mem=None):
        self.init = init
        self.pre_mem = pre_mem
        self.mem = mem


def _lod_stub(name):
    def f(*a, **kw):
        raise RuntimeError(
            f"fluid.layers.{name} is LoD-rank-table machinery (reference "
            "layers/control_flow.py); the padded redesign replaces LoD "
            "with dense [B, T, ...] + sequence_length — see "
            "paddle_tpu.ops.sequence (sequence_pad/sequence_unpad)")
    f.__name__ = name
    return f


lod_rank_table = _lod_stub("lod_rank_table")
lod_tensor_to_array = _lod_stub("lod_tensor_to_array")
array_to_lod_tensor = _lod_stub("array_to_lod_tensor")
max_sequence_len = _lod_stub("max_sequence_len")
merge_lod_tensor = _lod_stub("merge_lod_tensor")
split_lod_tensor = _lod_stub("split_lod_tensor")


def assign_skip_lod_tensor_array(input, output):
    """reference control_flow.py:assign_skip_lod_tensor_array — plain
    assign in the padded redesign."""
    output.set_value(input.numpy() if hasattr(input, "numpy") else input)
    return output


def copy_var_to_parent_block(var, layer_helper=None):
    """reference control_flow.py:copy_var_to_parent_block — single-block
    Program: identity."""
    return var


def select_input(inputs, mask):
    """reference control_flow.py:select_input — pick inputs[mask] (the
    merge node of a conditional block): lax.switch-style gather."""
    from ..dispatch import apply as _apply
    import jax.numpy as _jnp

    def impl(mask, *xs):
        idx = _jnp.clip(mask.reshape(()).astype(_jnp.int32), 0, len(xs) - 1)
        stacked = _jnp.stack(xs)
        return stacked[idx]

    return _apply(impl, (mask,) + tuple(inputs), name="select_input")


def select_output(input, outputs, mask):
    """reference control_flow.py:select_output — route input to
    outputs[mask]; functional redesign returns the outputs tuple with the
    selected slot replaced."""
    outs = list(outputs)
    i = int(mask.numpy()) if hasattr(mask, "numpy") else int(mask)
    outs[i] = input
    return tuple(outs)


shrink_memory = _lod_stub("shrink_memory")
