"""fluid trainer/device-worker descriptors + factory + communicator —
the parameter-server ASYNC-training API surface (reference:
python/paddle/fluid/{trainer_desc.py, device_worker.py,
trainer_factory.py, communicator.py}).

Reasoned redesign, not a silent no-op: the reference's async machinery
exists because GPU parameter-server training overlaps NCCL/RPC push-pull
with compute across trainer processes. On a TPU pod the model-parallel
substrate is GSPMD over ICI — parameters are sharded, not served — so
the PS-async *execution* path maps to the sharded-embedding data-parallel
design in parallel/embedding.py. What remains meaningful from this API
is the CONFIGURATION surface (which trainer/worker mode, what fetch
variables, debug mode), which tools and launch scripts written against
the reference still set. These classes therefore validate + carry that
configuration and hand it to the collective path, raising loudly on the
combinations that have no TPU meaning (geo-SGD staleness windows)."""
from __future__ import annotations

import warnings

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer", "DeviceWorker", "Hogwild",
           "DownpourSGD", "Section", "TrainerFactory", "Communicator"]


class TrainerDesc:
    """reference: trainer_desc.py:TrainerDesc (protobuf holder)."""

    def __init__(self):
        self._fetch_vars = []
        self._fetch_period = 100
        self._debug = False
        self._device_worker = None
        self._program = None
        self._infer = False

    def set_debug(self, debug):
        self._debug = bool(debug)

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, period):
        self._fetch_vars = list(zip(fetch_vars, fetch_info))
        self._fetch_period = period

    def set_device_worker(self, worker):
        self._device_worker = worker

    def set_program(self, program):
        self._program = program

    def set_infer(self, infer):
        self._infer = bool(infer)

    def _desc(self):
        return {
            "class": type(self).__name__,
            "debug": self._debug,
            "fetch": self._fetch_vars,
            "worker": type(self._device_worker).__name__
            if self._device_worker else None,
        }


class MultiTrainer(TrainerDesc):
    """reference: trainer_desc.py:MultiTrainer — multi-thread local
    training; on TPU the parallelism is the dp mesh axis."""


class DistMultiTrainer(TrainerDesc):
    """reference: trainer_desc.py:DistMultiTrainer — PS-async distributed
    training; redesigned onto collective dp (see module docstring)."""


class PipelineTrainer(TrainerDesc):
    """reference: trainer_desc.py:PipelineTrainer — maps to the pp mesh
    axis (parallel/pipeline.py)."""


class DeviceWorker:
    """reference: device_worker.py:DeviceWorker."""

    def __init__(self):
        self._infer = False
        self._program = None

    def _set_infer(self, infer=False):
        self._infer = bool(infer)

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """reference: device_worker.py:Hogwild — lock-free async updates.
    On TPU every step is a synchronous jitted update; Hogwild semantics
    degrade to synchronous dp (documented deviation, numerically the
    safer behavior)."""


class DownpourSGD(DeviceWorker):
    """reference: device_worker.py:DownpourSGD — PS push/pull worker.
    TPU redesign: sharded-embedding collective dp
    (parallel/embedding.py); constructing it is allowed (configs parse),
    running geo-async staleness is not."""


class DownpourSGDOPT(DeviceWorker):
    """reference: device_worker.py:DownpourSGDOPT — DownpourSGD with the
    unified accessor/optimizer config path. Same TPU redesign note as
    DownpourSGD: sharded-embedding collective dp stands in for the PS
    push/pull loop."""


class Section(DeviceWorker):
    """reference: device_worker.py:Section — pipeline section worker;
    maps to parallel/pipeline.py stage programs."""


class TrainerFactory:
    """reference: trainer_factory.py:TrainerFactory."""

    _TRAINERS = {
        "MultiTrainer": MultiTrainer,
        "DistMultiTrainer": DistMultiTrainer,
        "PipelineTrainer": PipelineTrainer,
    }
    _WORKERS = {
        "Hogwild": Hogwild,
        "DownpourSGD": DownpourSGD,
        "DownpourSGDOPT": DownpourSGDOPT,
        "Section": Section,
    }

    def _create_trainer(self, opt_info=None):
        if not opt_info:
            trainer = MultiTrainer()
            trainer.set_device_worker(Hogwild())
            return trainer
        tname = opt_info.get("trainer", "MultiTrainer")
        wname = opt_info.get("device_worker", "Hogwild")
        try:
            trainer = self._TRAINERS[tname]()
            worker = self._WORKERS[wname]()
        except KeyError as e:
            raise ValueError(f"unknown trainer/device_worker {e}") from e
        trainer.set_device_worker(worker)
        return trainer


class Communicator:
    """reference: communicator.py:Communicator — background geo-SGD
    async push/pull threads between trainers and parameter servers.

    TPU redesign: there is no PS role; gradients ride XLA collectives
    inside the jitted step, so start/stop manage nothing. The object
    validates its config and keeps the is_running contract so launch
    scripts sequence correctly; asking for geo staleness > 0 warns that
    the execution is synchronous."""

    def __init__(self, program=None, kwargs=None):
        self._running = False
        kwargs = kwargs or {}
        if int(kwargs.get("communicator_max_merge_var_num", 0) or 0) > 1 \
                or int(kwargs.get("geo_need_push_nums", 0) or 0) > 0:
            warnings.warn(
                "geo-SGD async staleness has no TPU execution path; "
                "training runs synchronously over the dp mesh "
                "(gradients psum'd in-step)", stacklevel=2)

    def start(self):
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running
