"""fluid.incubate.data_generator — same module as
paddle_tpu.incubate.data_generator (reference keeps two import paths)."""
from ....incubate.data_generator import (DataGenerator,  # noqa: F401
                                         MultiSlotDataGenerator,
                                         MultiSlotStringDataGenerator)
