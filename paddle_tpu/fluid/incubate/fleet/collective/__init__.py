"""fluid.incubate.fleet.collective (reference: collective/__init__.py:64
Collective(Fleet) + CollectiveOptimizer + the module-level `fleet`
singleton launch scripts import).

The TPU rebuild's Fleet (parallel/fleet.py) IS collective-mode, so this
module re-exports the same singleton under the reference import path."""
from .....parallel.fleet import (Fleet, DistributedStrategy,  # noqa: F401
                                DistributedOptimizer, fleet)

# reference: collective/__init__.py:384 CollectiveOptimizer(loss-scaled
# NCCL allreduce wrapper) — the GSPMD DistributedOptimizer plays its role
CollectiveOptimizer = DistributedOptimizer


class TrainStatus:
    """reference: collective/__init__.py:49."""

    def __init__(self, epoch_no=-1):
        self.epoch_no = epoch_no

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and \
            self.epoch_no == other.epoch_no
