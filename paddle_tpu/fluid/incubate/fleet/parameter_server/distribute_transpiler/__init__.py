"""fluid.incubate.fleet.parameter_server.distribute_transpiler
(reference: the PS-mode `fleet` singleton CTR jobs import).

TPU redesign (docs/scope.md): there is no parameter-server role on a TPU
pod — the PS path's big sharded embeddings become
parallel/embedding.py's row-sharded tables with all-to-all lookups, and
training is synchronous collective dp. This module exposes the SAME
`fleet` singleton so PS-mode launch scripts run; the async knobs parse
via fluid.trainer_desc and warn where semantics differ."""
from ......parallel.fleet import fleet, DistributedOptimizer  # noqa: F401
