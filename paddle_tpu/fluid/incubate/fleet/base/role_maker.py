"""fluid.incubate.fleet.base.role_maker (reference: role_maker.py:33 —
role discovery for distributed jobs).

TPU redesign: roles come from jax.distributed / the launch env
(parallel/env.py ParallelEnv); the MPI role makers have no TPU analogue
(jax.distributed owns rendezvous), so the collective-mode makers are the
real ones and MPI names alias them for import parity."""
from .....parallel.fleet import (RoleMakerBase,  # noqa: F401
                                PaddleCloudRoleMaker, UserDefinedRoleMaker)

# collective-only environment: MPI makers map to the env-driven one
MPIRoleMaker = PaddleCloudRoleMaker
MPISymetricRoleMaker = PaddleCloudRoleMaker
GeneralRoleMaker = PaddleCloudRoleMaker
UserDefinedCollectiveRoleMaker = UserDefinedRoleMaker


class Role:
    """reference: role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
