"""fluid.incubate.fleet (reference:
python/paddle/fluid/incubate/fleet/__init__.py)."""
from . import base  # noqa: F401
from . import collective  # noqa: F401
from . import parameter_server  # noqa: F401
