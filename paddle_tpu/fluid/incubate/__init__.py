"""fluid.incubate (reference: python/paddle/fluid/incubate — fleet +
data_generator)."""
from . import fleet  # noqa: F401
from . import data_generator  # noqa: F401
