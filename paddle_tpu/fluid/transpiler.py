"""fluid.transpiler facade.

The reference transpiler rewrites a Program for parameter-server /
multi-device training (reference: python/paddle/fluid/transpiler/
distribute_transpiler.py DistributeTranspiler, memory_optimization_
transpiler.py memory_optimize/release_memory). On TPU:

* PS-mode training is redesigned as sharded-embedding data parallelism
  (SURVEY §2 row 22) — `paddle_tpu.parallel.fleet` + `parallel.embedding`
  replace the trainer/pserver split, so DistributeTranspiler here
  validates its config and points each role at the collective path.
* memory_optimize / release_memory are no-ops: buffer reuse is XLA's
  arena + donated inputs (static/__init__.py donate_argnums), which
  already subsumes the reference's variable-reuse pass.
"""
from __future__ import annotations

from ..utils.log import get_logger

_log = get_logger("paddle_tpu.transpiler")


class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:DistributeTranspilerConfig."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True
        self.mode = "collective"


class DistributeTranspiler:
    """reference: distribute_transpiler.py:DistributeTranspiler. The
    trainer/pserver Program split has no TPU analogue — collectives ride
    ICI inside one compiled step — so transpile() records the config and
    the programs pass through unchanged; use parallel.fleet for real
    multi-device placement."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._role = None

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=""):
        self.trainer_id = trainer_id
        self.trainers = trainers
        self._program = program
        _log.info(
            "DistributeTranspiler: PS graph-split is replaced by the "
            "collective fleet path on TPU (parallel.fleet); programs "
            "pass through unchanged")

    def get_trainer_program(self, wait_port=True):
        from ..static import default_main_program
        return self._program or default_main_program()

    def get_pserver_program(self, endpoint):
        raise RuntimeError(
            "TPU rebuild has no parameter servers: embeddings shard over "
            "the mesh (parallel.embedding) and updates all-reduce over "
            "ICI. Launch every process as a worker via "
            "paddle_tpu.distributed.launch")

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        from ..static import default_startup_program
        return startup_program or default_startup_program()


def memory_optimize(input_program=None, skip_opt_set=None,
                    print_log=False, level=0, skip_grads=True):
    """reference: memory_optimization_transpiler.py:memory_optimize —
    XLA's buffer assignment + donated params already reuse memory; no-op
    (the reference itself deprecated this pass)."""
    return None


def release_memory(input_program, skip_opt_set=None):
    """reference: release_memory — same rationale as memory_optimize."""
    return None


class HashName:
    """reference: ps_dispatcher.py:HashName (kept for config parity)."""

    def __init__(self, pserver_endpoints):
        self.pserver_endpoints = list(pserver_endpoints)

    def dispatch(self, varlist):
        eps = self.pserver_endpoints
        return [eps[abs(hash(v.name)) % len(eps)] for v in varlist]


class RoundRobin:
    """reference: ps_dispatcher.py:RoundRobin."""

    def __init__(self, pserver_endpoints):
        self.pserver_endpoints = list(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self.pserver_endpoints[self._i])
            self._i = (self._i + 1) % len(self.pserver_endpoints)
        return out
