"""fluid.layers RNN-family functional/param-creating ops.

TPU-native rebuild of reference python/paddle/fluid/layers/rnn.py's
op-style surface: dynamic_lstm (:1964), lstm (:2121), dynamic_lstmp,
dynamic_gru (:2504), gru_unit (:2657), lstm_unit (:3034), beam_search,
beam_search_decode.

LoD redesign: the reference ops consume LoDTensors; here sequences are
padded [B, T, ...] plus an optional integer `sequence_length` (the same
padded+length convention as ops/sequence.py). Recurrence runs under
`lax.scan` (one compiled loop, TPU-friendly) instead of the reference's
per-timestep C++ ArrayRef walk. Gate order is (i, f, c, o) for LSTM and
(u, r, c) for GRU — weights are owned by this framework, so the layout is
documented rather than inherited.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor
from ..dispatch import apply
from .. import ops
from .. import initializer as I

# class re-exports (reference rnn.py defines these beside the ops)
from ..nn.rnn import RNNCellBase as RNNCell  # noqa: F401
from ..nn.rnn import LSTMCell, GRUCell  # noqa: F401
from ..nn.decode import (Decoder, DecodeHelper, TrainingHelper,  # noqa
                         GreedyEmbeddingHelper, SamplingEmbeddingHelper,
                         BasicDecoder, gather_tree)

SampleEmbeddingHelper = SamplingEmbeddingHelper  # reference spelling


def _acts(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda x: x}[name]


def _mask_scan(step, x_seq, carries, length, is_reverse):
    """scan `step` over time axis 1 of x_seq with carried state frozen
    past each row's length; outputs zeroed there (padded-LoD semantics)."""
    B, T = x_seq.shape[0], x_seq.shape[1]
    xs = jnp.moveaxis(x_seq, 1, 0)  # [T, B, ...]
    ts = jnp.arange(T)
    if is_reverse:
        xs = xs[::-1]
        ts = ts[::-1]

    def body(carry, xt):
        x_t, t = xt
        new_carry, out = step(carry, x_t)
        if length is not None:
            alive = (t < length).reshape(-1, *([1] * (out.ndim - 1)))
            new_carry = tuple(jnp.where(alive, n, c)
                              for n, c in zip(new_carry, carry))
            out = jnp.where(alive, out, 0.0)
        return new_carry, out

    carry, outs = lax.scan(body, carries, (xs, ts))
    if is_reverse:
        outs = outs[::-1]
    return carry, jnp.moveaxis(outs, 0, 1)


def _lstm_step_fn(w_r, b, peep, gate_act, cell_act, cand_act, proj=None,
                  proj_act=None):
    gact, cact, dact = _acts(gate_act), _acts(cell_act), _acts(cand_act)

    def step(carry, x_t):
        h, c = carry
        g = x_t + h @ w_r + b
        i, f, cand, o = jnp.split(g, 4, axis=-1)
        if peep is not None:
            w_ic, w_fc, w_oc = jnp.split(peep, 3, axis=-1)
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gact(i), gact(f)
        c_new = f * c + i * dact(cand)
        if peep is not None:
            o = o + c_new * w_oc
        o = gact(o)
        h_new = o * cact(c_new)
        if proj is not None:
            h_new = h_new @ proj
            if proj_act is not None:
                h_new = _acts(proj_act)(h_new)
        return (h_new, c_new), jnp.concatenate([h_new, c_new], axis=-1)

    return step


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 sequence_length=None):
    """reference layers/rnn.py:1964 — input is pre-projected [B, T, 4H];
    returns (hidden [B, T, H], cell [B, T, H])."""
    if h_0 is not None or c_0 is not None:
        raise NotImplementedError(
            "dynamic_lstm h_0/c_0: pass initial state via dynamic_lstmp or "
            "nn.LSTM; the padded redesign defaults to zeros")
    from .layers import _param
    H = size // 4
    w = _param(param_attr, (H, 4 * H), dtype, I.XavierUniform())
    nb = 7 * H if use_peepholes else 4 * H
    b = _param(bias_attr, (nb,), dtype, I.Constant(0.0), is_bias=True)

    def impl(x, w, b, length=None):
        b4, peep = (b[:4 * H], b[4 * H:]) if use_peepholes else (b, None)
        B = x.shape[0]
        h0 = jnp.zeros((B, H), x.dtype)
        c0 = jnp.zeros((B, H), x.dtype)
        step = _lstm_step_fn(w, b4, peep, gate_activation, cell_activation,
                             candidate_activation)
        _, hc = _mask_scan(step, x, (h0, c0), length, is_reverse)
        return hc[..., :H], hc[..., H:]

    if sequence_length is not None:
        return apply(impl, (input, w, b, sequence_length), n_out=2,
                     name="dynamic_lstm")
    return apply(impl, (input, w, b), n_out=2, name="dynamic_lstm")


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None, sequence_length=None):
    """reference layers/rnn.py dynamic_lstmp — LSTM with a recurrent
    projection (h_t = act(W_p · lstm_h)); recurrence runs on the projected
    state [B, P]. Returns (projection [B, T, P], cell [B, T, H])."""
    from .layers import _param
    H = size // 4
    P = proj_size
    w = _param(param_attr, (P, 4 * H), dtype, I.XavierUniform())
    w_proj = _param(param_attr, (H, P), dtype, I.XavierUniform())
    nb = 7 * H if use_peepholes else 4 * H
    b = _param(bias_attr, (nb,), dtype, I.Constant(0.0), is_bias=True)

    def impl(x, w, w_proj, b, length=None):
        b4, peep = (b[:4 * H], b[4 * H:]) if use_peepholes else (b, None)
        B = x.shape[0]
        r0 = jnp.zeros((B, P), x.dtype)
        c0 = jnp.zeros((B, H), x.dtype)
        step = _lstm_step_fn(w, b4, peep, gate_activation, cell_activation,
                             candidate_activation, proj=w_proj,
                             proj_act=proj_activation)
        _, rc = _mask_scan(step, x, (r0, c0), length, is_reverse)
        return rc[..., :P], rc[..., P:]

    if sequence_length is not None:
        return apply(lambda x, a, p, b, ln: impl(x, a, p, b, ln),
                     (input, w, w_proj, b, sequence_length), n_out=2,
                     name="dynamic_lstmp")
    return apply(impl, (input, w, w_proj, b), n_out=2, name="dynamic_lstmp")


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None, sequence_length=None):
    """reference layers/rnn.py:2504 — input pre-projected [B, T, 3H];
    returns hidden [B, T, H]. origin_mode picks between the two GRU
    update conventions (paddle supports both)."""
    from .layers import _param
    H = size
    w = _param(param_attr, (H, 3 * H), "float32", I.XavierUniform())
    b = _param(bias_attr, (3 * H,), "float32", I.Constant(0.0),
               is_bias=True)
    gact, cact = _acts(gate_activation), _acts(candidate_activation)

    def impl(x, w, b, *rest):
        h_init = None
        length = None
        ri = 0
        if h_0 is not None:
            h_init = rest[ri]
            ri += 1
        if sequence_length is not None:
            length = rest[ri]
        w_ur, w_c = w[:, :2 * H], w[:, 2 * H:]
        b_ur, b_c = b[:2 * H], b[2 * H:]
        B = x.shape[0]
        h0 = h_init if h_init is not None else jnp.zeros((B, H), x.dtype)

        def step(carry, x_t):
            (h,) = carry
            x_ur, x_c = x_t[..., :2 * H], x_t[..., 2 * H:]
            ur = gact(x_ur + h @ w_ur + b_ur)
            u, r = ur[..., :H], ur[..., H:]
            c = cact(x_c + (r * h) @ w_c + b_c)
            if origin_mode:
                h_new = (1.0 - u) * h + u * c
            else:
                h_new = u * h + (1.0 - u) * c
            return (h_new,), h_new

        _, hs = _mask_scan(step, x, (h0,), length, is_reverse)
        return hs

    args = [input, w, b]
    if h_0 is not None:
        args.append(h_0)
    if sequence_length is not None:
        args.append(sequence_length)
    return apply(impl, tuple(args), name="dynamic_gru")


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """reference layers/rnn.py:2657 — ONE GRU step. input [B, 3H] (pre-
    projected), hidden [B, H]. Returns (new_hidden, reset_hidden_prev,
    gate_concat) like the reference op's three outputs."""
    from .layers import _param
    H = size // 3
    w = _param(param_attr, (H, 3 * H), "float32", I.XavierUniform())
    b = _param(bias_attr, (3 * H,), "float32", I.Constant(0.0),
               is_bias=True)
    gact, cact = _acts(gate_activation), _acts(activation)

    def impl(x, h, w, b):
        w_ur, w_c = w[:, :2 * H], w[:, 2 * H:]
        ur = gact(x[..., :2 * H] + h @ w_ur + b[:2 * H])
        u, r = ur[..., :H], ur[..., H:]
        rh = r * h
        c = cact(x[..., 2 * H:] + rh @ w_c + b[2 * H:])
        if origin_mode:
            h_new = (1.0 - u) * h + u * c
        else:
            h_new = u * h + (1.0 - u) * c
        return h_new, rh, jnp.concatenate([u, r, c], axis=-1)

    return apply(impl, (input, hidden, w, b), n_out=3, name="gru_unit")


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference layers/rnn.py:3034 — ONE LSTM step with the input
    projection folded in (fc over [x, h]). Returns (hidden, cell)."""
    from .layers import _param
    H = hidden_t_prev.shape[-1]
    D = x_t.shape[-1]
    w = _param(param_attr, (D + H, 4 * H), "float32", I.XavierUniform())
    b = _param(bias_attr, (4 * H,), "float32", I.Constant(0.0),
               is_bias=True)

    def impl(x, h, c, w, b):
        g = jnp.concatenate([x, h], axis=-1) @ w + b
        i, f, cand, o = jnp.split(g, 4, axis=-1)
        c_new = jax.nn.sigmoid(f + forget_bias) * c + \
            jax.nn.sigmoid(i) * jnp.tanh(cand)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, c_new

    return apply(impl, (x_t, hidden_t_prev, cell_t_prev, w, b), n_out=2,
                 name="lstm_unit")


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference layers/rnn.py:2121 (the cudnn LSTM op) — stacked
    (bi)LSTM over padded [B, T, D]. init_h/init_c: [L*dirs, B, H].
    Returns (out [B, T, H*dirs], last_h, last_c) like the cudnn op."""
    from .layers import _param
    D = input.shape[-1]
    dirs = 2 if is_bidirec else 1
    ws = []
    for layer in range(num_layers):
        for d in range(dirs):
            in_d = D if layer == 0 else hidden_size * dirs
            ws.append(_param(None, (in_d, 4 * hidden_size), "float32",
                             default_initializer or I.XavierUniform()))
            ws.append(_param(None, (hidden_size, 4 * hidden_size),
                             "float32",
                             default_initializer or I.XavierUniform()))
            ws.append(_param(None, (4 * hidden_size,), "float32",
                             I.Constant(0.0), is_bias=True))

    def impl(x, h0, c0, *flat_w):
        outs = x
        last_h, last_c = [], []
        wi = 0
        for layer in range(num_layers):
            layer_outs = []
            for d in range(dirs):
                w_in, w_r, b = flat_w[wi], flat_w[wi + 1], flat_w[wi + 2]
                wi += 3
                idx = layer * dirs + d
                step = _lstm_step_fn(w_r, b, None, "sigmoid", "tanh",
                                     "tanh")
                x_proj = outs @ w_in
                (h_f, c_f), hc = _mask_scan(step, x_proj,
                                            (h0[idx], c0[idx]), None,
                                            is_reverse=(d == 1))
                layer_outs.append(hc[..., :hidden_size])
                last_h.append(h_f)
                last_c.append(c_f)
            outs = layer_outs[0] if dirs == 1 else jnp.concatenate(
                layer_outs, axis=-1)
        return outs, jnp.stack(last_h), jnp.stack(last_c)

    return apply(impl, (input, init_h, init_c) + tuple(ws), n_out=3,
                 name="lstm")


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """reference layers/rnn.py beam_search — one expansion step over a
    flattened [batch*beam, K] candidate table. LoD redesign: fixed
    [batch, beam] layout (the BeamSearchDecoder class is the primary API;
    this op-form mirrors the reference signature for ported loops).
    Returns (selected_ids, selected_scores[, parent_idx])."""
    def impl(pre_ids, pre_scores, ids, scores):
        nb_k = scores.shape[-1]
        B = scores.shape[0] // beam_size
        sc = scores.reshape(B, beam_size, nb_k)
        if not is_accumulated:
            sc = jnp.log(jnp.clip(sc, 1e-20, 1.0)) + \
                pre_scores.reshape(B, beam_size, 1)
        # a finished beam (pre_id == end_id) proposes exactly ONE
        # candidate — end_id at its own score (reference pruning rule):
        # keep its column 0 at pre_score, kill the rest, and force the
        # gathered token to end_id for candidates drawn from it below
        fin = (pre_ids.reshape(B, beam_size, 1) == end_id)
        only_first = jnp.full_like(sc, -1e9).at[..., 0].set(
            pre_scores.reshape(B, beam_size))
        sc = jnp.where(fin, only_first, sc)
        flat = sc.reshape(B, beam_size * nb_k)
        top_sc, top_ix = lax.top_k(flat, beam_size)
        parent = top_ix // nb_k                     # beam index
        cand_ids = ids.reshape(B, beam_size, nb_k)
        sel = jnp.take_along_axis(
            cand_ids.reshape(B, beam_size * nb_k), top_ix, axis=1)
        parent_fin = jnp.take_along_axis(fin[..., 0], parent, axis=1)
        sel = jnp.where(parent_fin, jnp.asarray(end_id, sel.dtype), sel)
        return (sel.reshape(B * beam_size, 1),
                top_sc.reshape(B * beam_size, 1),
                parent.reshape(B * beam_size).astype(jnp.int32))

    out = apply(impl, (pre_ids, pre_scores, ids, scores), n_out=3,
                name="beam_search")
    if return_parent_idx:
        return out
    return out[0], out[1]


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """reference layers/rnn.py beam_search_decode — backtrack the beam
    lattice. Redesign: `ids`/`scores` are stacked [T, batch*beam] step
    outputs with matching [T, batch*beam] parent indices embedded via
    gather_tree (use nn.decode.dynamic_decode for the full pipeline)."""
    ids_t, parents = ids
    full = gather_tree(ids_t, parents, end_token=end_id)
    return full, scores


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """reference layers/rnn.py:rnn — drive any RNNCell over a padded
    sequence with lax.scan (the nn.RNN layer is the class form)."""
    from ..nn.rnn import RNN as _RNN
    driver = _RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return driver(inputs, initial_states=initial_states,
                  sequence_length=sequence_length)
