"""fluid.lod_tensor (reference: fluid/lod_tensor.py).

LoD redesign note: this framework represents variable-length data as
padded dense arrays + explicit lengths (TPU-friendly static shapes; see
fluid/layers_rnn.py). These constructors keep the reference's API for
code that builds LoDTensors directly: the result is a Tensor carrying
the dense data plus a `.recursive_sequence_lengths()` accessor."""
import numpy as np

from ..tensor import Tensor

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


class _LoDTensor(Tensor):
    """Tensor + recursive sequence lengths (reference LoDTensor)."""

    def set_recursive_sequence_lengths(self, lens):
        self._recursive_seq_lens = [list(l) for l in lens]

    def recursive_sequence_lengths(self):
        return getattr(self, "_recursive_seq_lens", [])

    def has_valid_recursive_sequence_lengths(self):
        lens = self.recursive_sequence_lengths()
        if not lens:
            return False
        # innermost level must sum to the outer dim of the data
        return sum(lens[-1]) == int(self.shape[0])


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference lod_tensor.py:create_lod_tensor — build from a numpy
    array / list / Tensor plus level-of-detail lengths."""
    if isinstance(data, Tensor):
        arr = data.numpy()
    elif isinstance(data, list):
        # list-of-lists: each sublist is one sequence step group
        flat = np.concatenate(
            [np.asarray(x).reshape(len(x), -1) for x in data])
        new_lens = [len(x) for x in data]
        if recursive_seq_lens and recursive_seq_lens[-1] != new_lens:
            raise AssertionError(
                "data and recursive_seq_lens do not match")
        arr = flat
    else:
        arr = np.asarray(data)
    t = _LoDTensor(arr, stop_gradient=True)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise AssertionError(
            f"the provided recursive_seq_lens {recursive_seq_lens} is "
            f"invalid for data of outer dim {t.shape[0]}")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """reference lod_tensor.py:create_random_int_lodtensor."""
    overall = [sum(recursive_seq_lens[-1])] + list(base_shape)
    data = np.random.randint(low, high + 1, overall).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
