"""fluid.optimizer facade (reference: fluid/optimizer.py)."""
from ..optimizer import *  # noqa: F401,F403
