"""fluid.learning_rate_decay (reference: fluid/__init__.py re-exports
layers/learning_rate_scheduler.py under this name) — the functional
decay builders."""
from ..optimizer.lr import (noam_decay, exponential_decay,  # noqa: F401
                            piecewise_decay, cosine_decay,
                            polynomial_decay, linear_lr_warmup)
from .layers_extra2 import (natural_exp_decay,  # noqa: F401
                            inverse_time_decay)

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay", "cosine_decay",
    "linear_lr_warmup",
]
