"""fluid.layers parity tail — the remaining reference layer names.

Rebuild of the long tail of python/paddle/fluid/layers/{nn,tensor,ops,
loss,control_flow,detection,metric_op,learning_rate_scheduler}.py ops not
already covered by the core modules. Each function cites its reference
op; LoD-typed reference ops use the padded (B, T, …)+lengths formulation
throughout (the repo-wide convention), and SelectedRows (a sparse-update
host representation) degenerates to dense arrays under XLA, making its
helpers identities.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, Parameter, as_tensor, convert_dtype
from ..dispatch import apply
from .. import ops
from ..ops import nn_ops as F
from ..ops import loss as L
from .. import initializer as I
from .. import random as prandom

__all__ = [
    # tensor/meta
    "shape", "rank", "size", "is_empty", "has_nan", "has_inf",
    "reduce_all", "reduce_any", "sums", "multiplex", "unbind",
    "unique_with_counts", "scatter_nd", "create_tensor",
    "create_global_var", "create_parameter", "fill_constant_batch_size_like",
    "gaussian_random", "gaussian_random_batch_size_like",
    "uniform_random_batch_size_like", "autoincreased_step_counter",
    "sampling_id", "hash", "get_tensor_from_selected_rows",
    "merge_selected_rows", "tensor_array_to_tensor", "py_func",
    # activations / simple math
    "brelu", "soft_relu", "stanh", "clip_by_norm", "l2_normalize",
    "cos_sim",
    # shape/image ops
    "pad2d", "pad_constant_like", "crop", "crop_tensor", "random_crop",
    "space_to_depth", "shuffle_channel", "temporal_shift", "im2sequence",
    "image_resize", "image_resize_short", "resize_bilinear",
    "resize_nearest", "resize_linear", "resize_trilinear", "lrn",
    "adaptive_pool2d", "adaptive_pool3d", "pool3d", "affine_channel",
    "affine_grid", "grid_sampler", "row_conv", "fsp_matrix",
    "space_to_depth", "inplace_abn", "data_norm", "conv3d_transpose",
    "deformable_conv", "similarity_focus",
]


# ---------------------------------------------------------------------------
# tensor / meta

def shape(input, name=None):
    """reference: layers/nn.py shape_op — the shape as an int32 tensor."""
    return apply(lambda x: jnp.asarray(x.shape, jnp.int32), (input,),
                 nondiff=True, name="shape")


def rank(input, name=None):
    """reference: layers/nn.py rank."""
    return apply(lambda x: jnp.asarray(x.ndim, jnp.int32), (input,),
                 nondiff=True, name="rank")


def size(input, name=None):
    """reference: layers/nn.py size."""
    return apply(lambda x: jnp.asarray(x.size, jnp.int64), (input,),
                 nondiff=True, name="size")


def is_empty(x, name=None):
    """reference: control_flow.py is_empty."""
    return apply(lambda x: jnp.asarray(x.size == 0), (x,), nondiff=True,
                 name="is_empty")


def has_nan(x, name=None):
    """reference: layers/ops has_nan (debugger)."""
    return apply(lambda x: jnp.any(jnp.isnan(x)), (x,), nondiff=True,
                 name="has_nan")


def has_inf(x, name=None):
    return apply(lambda x: jnp.any(jnp.isinf(x)), (x,), nondiff=True,
                 name="has_inf")


def reduce_all(input, dim=None, keep_dim=False, name=None):
    """reference: layers/nn.py reduce_all."""
    return apply(lambda x: jnp.all(x, axis=_axes(dim), keepdims=keep_dim),
                 (input,), nondiff=True, name="reduce_all")


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return apply(lambda x: jnp.any(x, axis=_axes(dim), keepdims=keep_dim),
                 (input,), nondiff=True, name="reduce_any")


def _axes(dim):
    if dim is None:
        return None
    return tuple(dim) if isinstance(dim, (list, tuple)) else dim


def sums(input, out=None):
    """reference: layers/tensor.py sums — elementwise sum of a list."""
    def impl(*xs):
        acc = xs[0]
        for x in xs[1:]:
            acc = acc + x
        return acc
    res = apply(impl, tuple(input), name="sums")
    if out is not None:
        out.set_value(res.data)
        return out
    return res


def multiplex(inputs, index, name=None):
    """reference: layers/nn.py multiplex — row i of the output comes from
    inputs[index[i]]."""
    k = len(inputs)

    def impl(idx, *xs):
        stacked = jnp.stack(xs)  # (K, B, ...)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1).astype(jnp.int32), rows]

    return apply(impl, (index,) + tuple(inputs), name="multiplex")


def unbind(input, axis=0):
    """reference: layers/nn.py unbind."""
    n = input.shape[axis]
    return tuple(apply(lambda x, i=i: jnp.take(x, i, axis=axis), (input,),
                       name="unbind") for i in range(n))


def unique_with_counts(x, dtype="int32"):
    """reference: layers/nn.py unique_with_counts. Static-shape form:
    outputs are padded to len(x) (XLA needs fixed shapes); the valid
    prefix length is jnp.unique's size= contract."""
    def impl(x):
        n = x.shape[0]
        uniq, idx, counts = jnp.unique(
            x, return_inverse=True, return_counts=True, size=n,
            fill_value=0)
        return uniq, idx.astype(convert_dtype(dtype)), \
            counts.astype(convert_dtype(dtype))

    return apply(impl, (x,), n_out=3, nondiff=True,
                 name="unique_with_counts")


def scatter_nd(index, updates, shape, name=None):
    """reference: layers/nn.py scatter_nd."""
    shp = tuple(int(s) for s in shape)

    def impl(index, updates):
        out = jnp.zeros(shp, updates.dtype)
        return out.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)

    return apply(impl, (index, updates), name="scatter_nd")


def create_tensor(dtype, name=None, persistable=False):
    """reference: layers/tensor.py create_tensor."""
    return Tensor(jnp.zeros((), convert_dtype(dtype)), name=name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: layers/tensor.py create_global_var."""
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)),
               name=name)
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: layers/tensor.py create_parameter."""
    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierUniform())
    return Parameter(init(tuple(shape), convert_dtype(dtype)), name=name)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    """reference: layers/tensor.py fill_constant_batch_size_like."""
    shp = list(shape)

    def impl(x):
        shp2 = list(shp)
        shp2[output_dim_idx] = x.shape[input_dim_idx]
        return jnp.full(tuple(shp2), value, convert_dtype(dtype))

    return apply(impl, (input,), nondiff=True,
                 name="fill_constant_batch_size_like")


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """reference: layers/ops gaussian_random."""
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()
    return Tensor(mean + std * jax.random.normal(
        key, tuple(shape), convert_dtype(dtype)))


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    seed=0, dtype="float32"):
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shp, mean, std, seed, dtype)


def uniform_random_batch_size_like(input, shape, min=-1.0, max=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   seed=0, dtype="float32"):
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return ops.uniform(shp, dtype, min=min, max=max, seed=seed)


_step_counters = {}


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference: layers/tensor.py autoincreased_step_counter — a
    persistable counter bumped per call (per Executor.run in the
    reference; per invocation here)."""
    name = counter_name or "@STEP_COUNTER@"
    if name not in _step_counters:
        _step_counters[name] = Tensor(jnp.asarray(begin, jnp.int64),
                                      name=name)
        _step_counters[name].persistable = True
        return _step_counters[name]
    c = _step_counters[name]
    c.data = c.data + step
    return c


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    """reference: layers/nn.py sampling_id — sample a category per row of
    a probability matrix."""
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()

    def impl(x, key):
        return jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)),
                                      axis=-1)

    return apply(impl, (x, key), nondiff=True, name="sampling_id")


def hash(input, hash_size, num_hash=1, name=None):
    """reference: layers/nn.py hash op — int sequence → num_hash bucketed
    hashes (xxhash in C++; an affine multiply-shift family here keeps it
    deterministic and jit-safe)."""
    def impl(x):
        x = x.astype(jnp.uint32)
        outs = []
        for i in range(num_hash):
            a = np.uint32(2654435761 + 40503 * (i + 1))
            h = (x * a) ^ (x >> 16)
            outs.append((h % np.uint32(hash_size)).astype(jnp.int64))
        return jnp.stack(outs, axis=-1)

    return apply(impl, (input,), nondiff=True, name="hash")


def get_tensor_from_selected_rows(x, name=None):
    """reference: get_tensor_from_selected_rows_op — SelectedRows is a
    host sparse-update format; dense on XLA, so identity."""
    return ops.assign(x)


def merge_selected_rows(x, name=None):
    """reference: merge_selected_rows_op — identity for dense arrays."""
    return ops.assign(x)


def tensor_array_to_tensor(input, axis=1, use_stack=False):
    """reference: layers/tensor.py tensor_array_to_tensor."""
    from ..ops.imperative_flow import TensorArray
    if isinstance(input, TensorArray):
        items = list(input._items)
    else:
        items = list(input)
    if use_stack:
        out = ops.stack(items, axis=axis)
    else:
        out = ops.concat(items, axis=axis)
    sizes = Tensor(jnp.asarray([it.shape[axis] if not use_stack else 1
                                for it in items], jnp.int32))
    return out, sizes


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """reference: layers/nn.py py_func — run host python inside the graph.
    TPU-native: jax.pure_callback (host callback through XLA). `out` is a
    template Tensor (shape/dtype contract). backward_func(x..., dout...)
    → dx... installs as a custom VJP (also a host callback)."""
    xs = tuple(as_tensor(v) for v in (x if isinstance(x, (list, tuple))
                                      else [x]))
    outs = out if isinstance(out, (list, tuple)) else [out]
    out_shapes = [jax.ShapeDtypeStruct(
        tuple(o.shape), o.data.dtype if isinstance(o, Tensor) else o.dtype)
        for o in outs]
    single = not isinstance(out, (list, tuple))

    def call_fwd(*arrays):
        return jax.pure_callback(
            lambda *a: func(*[np.asarray(v) for v in a]),
            out_shapes[0] if single else tuple(out_shapes), *arrays)

    if backward_func is None:
        return apply(call_fwd, xs, nondiff=True,
                     n_out=1 if single else len(outs), name="py_func")

    @jax.custom_vjp
    def fwd_vjp(*arrays):
        return call_fwd(*arrays)

    def _f(*arrays):
        out = call_fwd(*arrays)
        outs_tup = (out,) if single else tuple(out)
        return out, (arrays, outs_tup)

    def _b(res, g):
        arrays, outs_tup = res
        in_shapes = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                          for a in arrays)
        gs = (g,) if single else tuple(g)

        def host(*vals):
            # reference convention: backward_func(*inputs, *outputs,
            # *output_grads) -> input grads
            grads = backward_func(*[np.asarray(v) for v in vals])
            if not isinstance(grads, (list, tuple)):
                grads = (grads,)
            return tuple(np.asarray(gr, dtype=s.dtype)
                         for gr, s in zip(grads, in_shapes))

        return jax.pure_callback(host, in_shapes,
                                 *(arrays + outs_tup + gs))

    fwd_vjp.defvjp(_f, _b)
    return apply(fwd_vjp, xs, n_out=1 if single else len(outs),
                 name="py_func")


# ---------------------------------------------------------------------------
# activations / simple math

def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """reference: ops.py brelu."""
    return apply(lambda x: jnp.clip(x, t_min, t_max), (x,), name="brelu")


def soft_relu(x, threshold=40.0, name=None):
    """reference: ops.py soft_relu: log(1 + exp(clip(x)))."""
    return apply(lambda x: jnp.log1p(jnp.exp(jnp.clip(x, -threshold,
                                                      threshold))),
                 (x,), name="soft_relu")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """reference: ops.py stanh."""
    return apply(lambda x: scale_b * jnp.tanh(scale_a * x), (x,),
                 name="stanh")


def clip_by_norm(x, max_norm, name=None):
    """reference: clip_by_norm_op."""
    def impl(x):
        n = jnp.sqrt(jnp.sum(x * x))
        return jnp.where(n > max_norm, x * (max_norm / jnp.maximum(
            n, 1e-12)), x)

    return apply(impl, (x,), name="clip_by_norm")


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    """reference: layers/nn.py l2_normalize."""
    def impl(x):
        n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
        return x / jnp.maximum(n, epsilon)

    return apply(impl, (x,), name="l2_normalize")


def cos_sim(X, Y, name=None):
    """reference: cos_sim_op — rowwise cosine similarity, (B, 1)."""
    def impl(x, y):
        y = jnp.broadcast_to(y, x.shape)
        num = jnp.sum(x * y, axis=-1, keepdims=True)
        den = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True) *
                       jnp.sum(y * y, -1, keepdims=True))
        return num / jnp.maximum(den, 1e-12)

    return apply(impl, (X, Y), name="cos_sim")


# ---------------------------------------------------------------------------
# shape / image ops

def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    """reference: pad2d_op. paddings = (top, bottom, left, right)."""
    t, b, l, r = [int(p) for p in paddings]
    jmode = {"constant": "constant", "reflect": "reflect",
             "edge": "edge"}[mode]

    def impl(x):
        if data_format == "NCHW":
            pads = [(0, 0), (0, 0), (t, b), (l, r)]
        else:
            pads = [(0, 0), (t, b), (l, r), (0, 0)]
        kw = dict(constant_values=pad_value) if jmode == "constant" else {}
        return jnp.pad(x, pads, mode=jmode, **kw)

    return apply(impl, (input,), name="pad2d")


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference: pad_constant_like_op — pad y up to x's shape."""
    def impl(x, y):
        pads = [(0, a - b) for a, b in zip(x.shape, y.shape)]
        return jnp.pad(y, pads, constant_values=pad_value)

    return apply(impl, (x, y), name="pad_constant_like")


def crop(x, shape=None, offsets=None, name=None):
    """reference: crop_op."""
    return crop_tensor(x, shape, offsets, name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """reference: crop_tensor_op."""
    shp = [int(s) for s in (shape if not isinstance(shape, Tensor)
                            else np.asarray(jax.device_get(shape.data)))]
    offs = [0] * len(shp) if offsets is None else [
        int(o) for o in (offsets if not isinstance(offsets, Tensor)
                         else np.asarray(jax.device_get(offsets.data)))]

    def impl(x):
        idx = tuple(slice(o, o + s) for o, s in zip(offs, shp))
        return x[idx]

    return apply(impl, (x,), name="crop_tensor")


def random_crop(x, shape, seed=None):
    """reference: random_crop_op — same random crop for the whole batch
    (per-sample crops are a gather away; batch-uniform keeps it jit-static)."""
    key = prandom.next_key() if seed is None else jax.random.PRNGKey(seed)
    shp = [int(s) for s in shape]

    def impl(x, key):
        spatial = x.shape[1:]
        keys = jax.random.split(key, len(shp))
        starts = [jax.random.randint(keys[i], (), 0,
                                     spatial[i] - shp[i] + 1)
                  for i in range(len(shp))]
        return lax.dynamic_slice(
            x, [jnp.asarray(0)] + starts, [x.shape[0]] + shp)

    return apply(impl, (x, key), name="random_crop")


def space_to_depth(x, blocksize, name=None):
    """reference: space_to_depth_op (NCHW)."""
    bs = int(blocksize)

    def impl(x):
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // bs, bs, w // bs, bs)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * bs * bs, h // bs, w // bs)

    return apply(impl, (x,), name="space_to_depth")


def shuffle_channel(x, group, name=None):
    """reference: shuffle_channel_op (ShuffleNet)."""
    g = int(group)

    def impl(x):
        n, c, h, w = x.shape
        return x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4) \
            .reshape(n, c, h, w)

    return apply(impl, (x,), name="shuffle_channel")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """reference: temporal_shift_op (TSM)."""
    def impl(x):
        nt, c, h, w = x.shape
        n = nt // seg_num
        x = x.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([x[:, 1:, :fold],
                                jnp.zeros_like(x[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]),
                                 x[:, :-1, fold:2 * fold]], axis=1)
        rest = x[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)

    return apply(impl, (x,), name="temporal_shift")


def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    """reference: im2sequence_op — unfold patches to a (B, L, K) sequence."""
    ks = F._pair(filter_size, 2)
    st = F._pair(stride, 2)

    def impl(x):
        cols = lax.conv_general_dilated_patches(
            x, ks, st, padding=[(padding, padding), (padding, padding)])
        n, ck, oh, ow = cols.shape
        return cols.reshape(n, ck, oh * ow).transpose(0, 2, 1)

    return apply(impl, (input,), name="im2sequence")


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", align_corners=True, align_mode=1,
                 data_format="NCHW"):
    """reference: layers/nn.py image_resize → ops.interpolate."""
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear"}[resample.upper()]
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=mode, align_corners=align_corners,
                         data_format=data_format)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference: layers/nn.py image_resize_short."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    ratio = out_short_len / float(short)
    return image_resize(input, out_shape=[int(round(h * ratio)),
                                          int(round(w * ratio))],
                        resample=resample)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1, data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True, data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        align_corners, 1, data_format)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1, data_format="NCW"):
    def impl(x):
        # (N, C, W) → bilinear over a dummy H
        x4 = x[:, :, None, :]
        target = out_shape[0] if out_shape else int(x.shape[-1] * scale)
        y = jax.image.resize(x4, x4.shape[:2] + (1, target),
                             method="linear")
        return y[:, :, 0, :]

    return apply(impl, (input,), name="resize_linear")


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    def impl(x):
        if out_shape is not None:
            tgt = tuple(int(s) for s in out_shape)
        else:
            tgt = tuple(int(s * scale) for s in x.shape[2:])
        return jax.image.resize(x, x.shape[:2] + tgt, method="trilinear")

    return apply(impl, (input,), name="resize_trilinear")


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    """reference: lrn_op → local_response_norm (NCHW)."""
    return F.local_response_norm(input, size=n, alpha=alpha, beta=beta,
                                 k=k)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """reference: layers/nn.py adaptive_pool2d."""
    if pool_type == "max":
        return F.adaptive_max_pool2d(input, pool_size)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """reference: layers/nn.py adaptive_pool3d (avg/max over D,H,W)."""
    ps = F._pair(pool_size, 3)

    def impl(x):
        n, c, d, h, w = x.shape
        od, oh, ow = ps
        x = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        red = (3, 5, 7)
        return jnp.max(x, axis=red) if pool_type == "max" else \
            jnp.mean(x, axis=red)

    return apply(impl, (input,), name="adaptive_pool3d")


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None, exclusive=True, data_format="NCDHW"):
    """reference: pool3d_op."""
    if global_pooling:
        return apply(lambda x: (jnp.max if pool_type == "max" else
                                jnp.mean)(x, axis=(2, 3, 4),
                                          keepdims=True),
                     (input,), name="pool3d_global")
    ks = F._pair(pool_size, 3)
    st = F._pair(pool_stride, 3)
    pd = F._pair(pool_padding, 3)

    def impl(x):
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pd]
        if pool_type == "max":
            return lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 1) + ks, (1, 1) + st, pads)
        s = lax.reduce_window(x, 0.0, lax.add, (1, 1) + ks, (1, 1) + st,
                              pads)
        ones = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                 (1, 1) + ks, (1, 1) + st, pads)
        denom = ones if exclusive else float(np.prod(ks))
        return s / denom

    return apply(impl, (input,), name="pool3d")


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    """reference: affine_channel_op — per-channel scale+bias."""
    def impl(x, s, b):
        shp = (1, -1, 1, 1) if data_layout == "NCHW" else (1, 1, 1, -1)
        return x * s.reshape(shp) + b.reshape(shp)

    out = apply(impl, (x, scale, bias), name="affine_channel")
    from .layers import _act
    return _act(out, act)


def affine_grid(theta, out_shape, name=None):
    """reference: affine_grid_op — 2D sampling grid from affine params
    theta (N, 2, 3); out_shape (N, C, H, W)."""
    shp = [int(s) for s in out_shape] if not isinstance(
        out_shape, Tensor) else [int(s) for s in np.asarray(
            jax.device_get(out_shape.data))]
    h, w = shp[2], shp[3]

    def impl(theta):
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # H,W,3
        return jnp.einsum("hwk,njk->nhwj", base, theta)  # N,H,W,2

    return apply(impl, (theta,), name="affine_grid")


def grid_sampler(x, grid, name=None):
    """reference: grid_sampler_op — bilinear sampling of x (N,C,H,W) at
    normalized grid (N,H',W',2) coords in [-1, 1]."""
    def impl(x, grid):
        n, c, h, w = x.shape
        gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
        gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        lx = gx - x0
        ly = gy - y0

        def gather(yi, xi):
            yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            # batch-wise gather: out[n, c, i, j] = x[n, c, yi[n,i,j], xi[n,i,j]]
            def one(img, yy, xx):
                return img[:, yy, xx]
            return jax.vmap(one)(x, yi, xi)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        lx_ = lx[:, None]
        ly_ = ly[:, None]
        # zero-pad outside the input square (reference padding mode)
        inside = ((gx >= 0) & (gx <= w - 1) & (gy >= 0) &
                  (gy <= h - 1))[:, None]
        out = (v00 * (1 - lx_) * (1 - ly_) + v01 * lx_ * (1 - ly_) +
               v10 * (1 - lx_) * ly_ + v11 * lx_ * ly_)
        return jnp.where(inside, out, 0.0)

    return apply(impl, (x, grid), name="grid_sampler")


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: row_conv_op (lookahead conv for streaming ASR). Padded
    (B, T, D) formulation: out[t] = Σ_{i=0..F} x[t+i] · w[i]."""
    from .layers import _param, _act
    d = input.shape[-1]
    fc = int(future_context_size)
    w = _param(param_attr, (fc + 1, d), "float32", I.XavierUniform())

    def impl(x, w):
        b, t, dd = x.shape
        out = jnp.zeros_like(x)
        for i in range(fc + 1):
            shifted = jnp.pad(x, [(0, 0), (0, i), (0, 0)])[:, i:i + t]
            out = out + shifted * w[i]
        return out

    return _act(apply(impl, (input, w), name="row_conv"), act)


def fsp_matrix(x, y):
    """reference: fsp_op (distillation flow matrix): (B, Cx, Cy)."""
    def impl(x, y):
        b, cx, h, w = x.shape
        cy = y.shape[1]
        xf = x.reshape(b, cx, h * w)
        yf = y.reshape(b, cy, h * w)
        return jnp.einsum("bxs,bys->bxy", xf, yf) / (h * w)

    return apply(impl, (x, y), name="fsp_matrix")


def inplace_abn(input, act=None, is_test=False, momentum=0.9,
                epsilon=1e-5, param_attr=None, bias_attr=None,
                data_layout="NCHW", name=None, act_alpha=1.0):
    """reference: inplace_abn_op — batch_norm + activation (the in-place
    memory trick is XLA's job)."""
    from .layers import batch_norm
    out = batch_norm(input, act=None, is_test=is_test, momentum=momentum,
                     epsilon=epsilon, param_attr=param_attr,
                     bias_attr=bias_attr, data_layout=data_layout)
    if act == "leaky_relu":
        return F.leaky_relu(out, act_alpha)
    if act == "elu":
        return F.elu(out, act_alpha)
    from .layers import _act
    return _act(out, act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """reference: data_norm_op (CTR models): normalize by accumulated
    batch statistics without scale/shift by default."""
    def impl(x):
        mean = jnp.mean(x, axis=0, keepdims=True)
        var = jnp.var(x, axis=0, keepdims=True)
        return (x - mean) / jnp.sqrt(var + epsilon)

    out = apply(impl, (input,), name="data_norm")
    from .layers import _act
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    """reference: conv3d_transpose layer (conv_transpose3d via lhs-dilated
    conv)."""
    from .layers import _param, _act
    cin = input.shape[1]
    ks = F._pair(filter_size, 3)
    w = _param(param_attr, (cin, num_filters // groups) + tuple(ks),
               "float32", I.XavierUniform())
    b = _param(bias_attr, (num_filters,), "float32", I.Constant(0.0),
               is_bias=True)
    st = F._pair(stride, 3)
    pd = F._pair(padding, 3)
    dl = F._pair(dilation, 3)

    def impl(x, w, b):
        kdims = w.shape[2:]
        pads = [(dl[i] * (kdims[i] - 1) - pd[i],
                 dl[i] * (kdims[i] - 1) - pd[i]) for i in range(3)]
        wf = jnp.flip(w, axis=(2, 3, 4))  # (CIN, NF/g, kd, kh, kw)
        if groups > 1:
            # grouped transpose conv: per-group (NF/g, CIN/g, k...) then
            # stack output channels group-major
            cin = wf.shape[0]
            wf = wf.reshape(groups, cin // groups, -1, *kdims)
            wf = jnp.moveaxis(wf, 2, 1)      # (g, NF/g, CIN/g, k...)
            rhs = wf.reshape(-1, cin // groups, *kdims)  # (NF, CIN/g, ...)
        else:
            rhs = jnp.moveaxis(wf, 1, 0)     # (NF, CIN, k...)
        out = lax.conv_general_dilated(
            x, rhs, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=st, rhs_dilation=dl, feature_group_count=groups,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        return out + b.reshape(1, -1, 1, 1, 1)

    return _act(apply(impl, (input, w, b), name="conv3d_transpose"), act)


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """reference: deformable_conv_op (v1/v2). Gather-based TPU
    formulation: for each kernel tap, bilinear-sample the input at the
    offset position (grid_sampler math), modulate (v2), then one einsum
    against the weights — all dense static-shape ops."""
    from .layers import _param
    cin = input.shape[1]
    ks = F._pair(filter_size, 2)
    st = F._pair(stride, 2)
    pd = F._pair(padding, 2)
    dl = F._pair(dilation, 2)
    kh, kw = ks
    w = _param(param_attr, (num_filters, cin // groups, kh, kw),
               "float32", I.XavierUniform())
    b = _param(bias_attr, (num_filters,), "float32", I.Constant(0.0),
               is_bias=True)
    use_mask = modulated and mask is not None

    def impl(x, offset, *rest):
        if use_mask:
            msk, w_, b_ = rest
        else:
            w_, b_ = rest
            msk = None
        n, c, h, wd = x.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (wd + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xp = jnp.pad(x, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        hp, wp = xp.shape[2], xp.shape[3]
        oy = jnp.arange(oh) * st[0]
        ox = jnp.arange(ow) * st[1]
        # offset layout: (N, 2*dg*kh*kw, OH, OW) — (y, x) per tap
        off = offset.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
        samples = []
        for ki in range(kh):
            for kj in range(kw):
                tap = ki * kw + kj
                base_y = oy[:, None] + ki * dl[0]
                base_x = ox[None, :] + kj * dl[1]
                # deformable_groups=1 fast path; groups>1 tiles channels
                dy = off[:, :, tap, 0]
                dx = off[:, :, tap, 1]
                gy = base_y[None, None] + dy
                gx = base_x[None, None] + dx
                gy = gy[:, 0]
                gx = gx[:, 0]
                y0 = jnp.floor(gy)
                x0 = jnp.floor(gx)
                ly = gy - y0
                lx = gx - x0

                def gath(yi, xi):
                    yi = jnp.clip(yi, 0, hp - 1).astype(jnp.int32)
                    xi = jnp.clip(xi, 0, wp - 1).astype(jnp.int32)

                    def one(img, yy, xx):
                        return img[:, yy, xx]
                    return jax.vmap(one)(xp, yi, xi)

                v = (gath(y0, x0) * ((1 - ly) * (1 - lx))[:, None] +
                     gath(y0, x0 + 1) * ((1 - ly) * lx)[:, None] +
                     gath(y0 + 1, x0) * (ly * (1 - lx))[:, None] +
                     gath(y0 + 1, x0 + 1) * (ly * lx)[:, None])
                inside = ((gy >= 0) & (gy <= hp - 1) & (gx >= 0) &
                          (gx <= wp - 1))[:, None]
                v = jnp.where(inside, v, 0.0)
                if msk is not None:
                    m = msk.reshape(n, deformable_groups, kh * kw, oh,
                                    ow)[:, 0, tap]
                    v = v * m[:, None]
                samples.append(v)  # (N, C, OH, OW)
        s = jnp.stack(samples, axis=2)  # (N, C, K, OH, OW)
        s = s.reshape(n, c, kh, kw, oh, ow)
        return jnp.einsum("nckjhw,ockj->nohw", s, w_) + \
            b_.reshape(1, -1, 1, 1)

    args = (input, offset)
    if use_mask:
        args = args + (mask,)
    args = args + (w, b)
    return apply(impl, args, name="deformable_conv")


def similarity_focus(input, axis, indexes, name=None):
    """reference: similarity_focus_op — build a focus mask: for each
    selected channel (via `indexes` along `axis`), mark the max position
    per row/col. Simplified faithful form: mask marks the argmax positions
    of the selected slices."""
    idxs = [int(i) for i in indexes]

    def impl(x):
        n = x.shape[0]
        mask = jnp.zeros_like(x)
        for i in idxs:
            sl = jnp.take(x, i, axis=axis)  # (N, H, W) for axis=1
            flat = sl.reshape(n, -1)
            am = jnp.argmax(flat, axis=1)
            m = jax.nn.one_hot(am, flat.shape[1],
                               dtype=x.dtype).reshape(sl.shape)
            mask = mask + jnp.expand_dims(m, axis)
        return jnp.minimum(mask, 1.0)

    return apply(impl, (input,), name="similarity_focus")
