"""fluid.backward facade (reference: fluid/backward.py)."""
from ..static import append_backward, gradients  # noqa: F401


from ..static import gradients as calc_gradient  # noqa: E402


class ProgramStats:
    """reference backward.py:ProgramStats — recompute-segment bookkeeping.
    The rebuild gets recomputation from jax.checkpoint (optimizer.Recompute),
    so this only records the op list for ported introspection code."""

    def __init__(self, block=None, ops=None):
        self.block = block
        self.ops = ops or []
        self.var_op_deps = {}

    def get_reserved_vars(self):
        return []

    def get_out_of_subgraph_vars(self, begin_idx, end_idx):
        return []


def serialize_op_decs(op_desc=None):
    """reference backward.py:serialize_op_decs — no protobuf op descs
    exist; returns the op's repr."""
    return repr(op_desc)
