"""fluid.metrics facade (reference: fluid/metrics.py)."""
from ..metric import *  # noqa: F401,F403
