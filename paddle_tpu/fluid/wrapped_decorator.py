"""fluid.wrapped_decorator (reference: fluid/wrapped_decorator.py) —
signature-preserving decorator helpers used across the fluid surface."""
import contextlib
import functools

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    """reference wrapped_decorator.py:wrap_decorator — returns a
    decorator whose wrapped function keeps the original's metadata
    (the reference used the `decorator` package; functools.wraps gives
    the py3-native equivalent)."""
    @functools.wraps(decorator_func)
    def __impl__(func):
        wrapped = decorator_func(func)
        if callable(wrapped):
            try:
                functools.update_wrapper(wrapped, func)
            except (AttributeError, TypeError):
                pass
        return wrapped
    return __impl__


def signature_safe_contextmanager(func):
    """reference wrapped_decorator.py:signature_safe_contextmanager."""
    return functools.wraps(func)(contextlib.contextmanager(func))
