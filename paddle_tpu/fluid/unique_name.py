"""fluid.unique_name (reference: fluid/unique_name.py) — process-wide
unique name generator with guard() scoping."""
import contextlib

_counters = {}
_prefix = [""]


def generate(key):
    k = _prefix[0] + key
    _counters[k] = _counters.get(k, -1) + 1
    return f"{k}_{_counters[k]}"


def generate_with_ignorable_key(key):
    return generate(key)


def switch(new_generator=None):
    """Swap the counter state out, returning the old snapshot; pass a
    previously returned snapshot back in to restore it (the reference's
    switch-out/switch-back idiom)."""
    old = dict(_counters)
    _counters.clear()
    if isinstance(new_generator, dict):
        _counters.update(new_generator)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old_c = dict(_counters)
    old_p = _prefix[0]
    _counters.clear()
    if isinstance(new_generator, str):
        _prefix[0] = new_generator
    try:
        yield
    finally:
        _counters.clear()
        _counters.update(old_c)
        _prefix[0] = old_p
