"""fluid.io facade (reference: fluid/io.py save/load surface)."""
from ..io import *  # noqa: F401,F403
