"""fluid.io facade (reference: fluid/io.py save/load surface, plus its
`batch`/`shuffle` reader-decorator aliases of paddle.reader)."""
from ..io import *  # noqa: F401,F403
from ..reader import batch, shuffle  # noqa: F401
from .data_feeder import PyReader  # noqa: F401


def save(program, model_path):
    """reference fluid/io.py:save — persist a Program's parameters
    (".pdparams") and optimizer slot state (".pdopt", only when any
    exists). The ".pdmodel" network description has no serialized-proto
    analogue here: programs re-trace from python (jit/to_static), which
    is the deployment path (inference.py Predictor)."""
    import numpy as np
    if not model_path or model_path.rsplit("/", 1)[-1] == "":
        raise ValueError(f"model_path MUST be format of dirname/filename "
                         f"[dirname\\filename in Windows system], but "
                         f"received model_path is empty string: "
                         f"{model_path!r}")
    params = {n: np.asarray(v.numpy())
              for n, v in program.param_vars.items()}
    # write to the exact reference suffix: np.savez(str) would append
    # .npz and break load's path arithmetic; a file object does not
    with open(model_path + ".pdparams", "wb") as fh:
        np.savez(fh, **params)
    opt_state = {}
    for oi, (opt, _) in enumerate(getattr(program, "optimizers", [])):
        for sd_key, val in opt.state_dict().items():
            if hasattr(val, "numpy"):
                opt_state[f"opt{oi}@{sd_key}"] = np.asarray(val.numpy())
    if opt_state:
        with open(model_path + ".pdopt", "wb") as fh:
            np.savez(fh, **opt_state)


def load(program, model_path, executor=None, var_list=None):
    """reference fluid/io.py:load — restore parameters saved by
    fluid.save into the program's param holders, shape/dtype checked."""
    import os
    import numpy as np
    path = model_path + ".pdparams"
    if not os.path.exists(path):
        path = model_path if os.path.exists(model_path) else path
    with np.load(path) as data:
        names = set(data.files)
        targets = (
            {getattr(v, "name", str(v)) for v in var_list}
            if var_list is not None else None)
        for n, holder in program.param_vars.items():
            if targets is not None and n not in targets:
                continue
            if n not in names:
                raise RuntimeError(f"parameter {n!r} not found in "
                                   f"{path}")
            arr = data[n]
            if tuple(arr.shape) != tuple(holder.data.shape):
                raise RuntimeError(
                    f"shape mismatch for {n!r}: checkpoint "
                    f"{arr.shape} vs program {tuple(holder.data.shape)}")
            holder.set_value(arr)
    opt_path = model_path + ".pdopt"
    if os.path.exists(opt_path):
        with np.load(opt_path) as data:
            for oi, (opt, _) in enumerate(
                    getattr(program, "optimizers", [])):
                prefix = f"opt{oi}@"
                state = {k[len(prefix):]: data[k] for k in data.files
                         if k.startswith(prefix)}
                if state:
                    opt.set_state_dict(state)
