"""fluid.regularizer facade (reference: fluid/regularizer.py)."""
from ..regularizer import *  # noqa: F401,F403
from ..regularizer import L1Decay, L2Decay, L1DecayRegularizer, \
    L2DecayRegularizer  # noqa: F401


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference regularizer.py:append_regularization_ops — functional
    redesign: g += reg.grad_term(p) for each param (per-param regularizer
    wins over the global one, like the reference)."""
    out = []
    for p, g in parameters_and_grads:
        reg = getattr(p, "regularizer", None) or regularization
        if reg is not None and g is not None and not getattr(
                p, "stop_gradient", False):
            g = g + reg.grad_term(p)
        out.append((p, g))
    return out
