"""fluid.data_feed_desc (reference:
python/paddle/fluid/data_feed_desc.py — wraps the DataFeedDesc protobuf
describing MultiSlot datasets: slot names/types/dims, batch size, the
pipe command).

The rebuild parses the same protobuf-TEXT format (so existing .prototxt
feed configs load unchanged) into a plain dict the dataset/ parsers and
io.DataLoader consume — no protobuf dependency needed for the subset the
reference actually uses."""
from __future__ import annotations

import re

__all__ = ["DataFeedDesc"]


class DataFeedDesc:
    """Parse + edit a MultiSlot data feed description.

    Accepts the reference's proto-text, e.g.::

        name: "MultiSlotDataFeed"
        batch_size: 2
        multi_slot_desc {
          slots { name: "words"  type: "uint64" is_dense: false is_used: true }
          slots { name: "label"  type: "uint64" is_dense: false is_used: true }
        }
    """

    def __init__(self, proto_info):
        self.proto_desc = {"name": "MultiSlotDataFeed", "batch_size": 1}
        self.slots = []  # list of dicts: name/type/is_dense/is_used/dims
        self._parse(proto_info)

    # -- proto-text subset parser -------------------------------------------
    def _parse(self, text):
        top = re.sub(r"multi_slot_desc\s*{(.*)}", "", text,
                     flags=re.DOTALL)
        for key, val in re.findall(r"(\w+)\s*:\s*(\"[^\"]*\"|\S+)", top):
            self.proto_desc[key] = self._val(val)
        for slot_txt in re.findall(r"slots\s*{([^}]*)}", text):
            slot = {"name": "", "type": "uint64", "is_dense": False,
                    "is_used": False, "dims": []}
            for key, val in re.findall(r"(\w+)\s*:\s*(\"[^\"]*\"|\S+)",
                                       slot_txt):
                if key == "dims":
                    slot["dims"].append(int(val))
                else:
                    slot[key] = self._val(val)
            self.slots.append(slot)

    @staticmethod
    def _val(tok):
        if tok.startswith('"'):
            return tok.strip('"')
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            return tok

    # -- reference API ------------------------------------------------------
    def set_batch_size(self, batch_size):
        self.proto_desc["batch_size"] = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for s in self.slots:
            if s["name"] in dense_slots_name:
                s["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        for s in self.slots:
            if s["name"] in use_slots_name:
                s["is_used"] = True

    def desc(self):
        """Text form (reference returns proto text; we return the same
        fields re-serialized)."""
        lines = [f'name: "{self.proto_desc["name"]}"',
                 f'batch_size: {self.proto_desc["batch_size"]}',
                 "multi_slot_desc {"]
        for s in self.slots:
            dims = "".join(f" dims: {d}" for d in s["dims"])
            lines.append(
                f'  slots {{ name: "{s["name"]}" type: "{s["type"]}" '
                f'is_dense: {str(s["is_dense"]).lower()} '
                f'is_used: {str(s["is_used"]).lower()}{dims} }}')
        lines.append("}")
        return "\n".join(lines)

    def used_slots(self):
        return [s["name"] for s in self.slots if s["is_used"]]
