"""fluid.framework — importable-module facade (reference:
fluid/framework.py: Program/Variable and mode switches)."""
from ..static import (Program, program_guard, default_main_program,  # noqa
                      default_startup_program, enable_static,
                      disable_static)
from ..static import StaticVar as Variable  # noqa: F401
from ..static import in_static_mode as _in_static_mode


def in_dygraph_mode():
    """reference framework.py:in_dygraph_mode."""
    return not _in_static_mode()


from ..tensor import Tensor, Parameter, convert_dtype  # noqa: F401,E402
from ..device import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401,E402
from ..param_attr import ParamAttr  # noqa: F401,E402


# --- remaining framework.py parity ------------------------------------------
from ..static import Scope, global_scope, name_scope  # noqa: F401,E402
from ..static import append_backward, gradients  # noqa: F401,E402
from ..tensor import convert_dtype as convert_np_dtype_to_dtype_  # noqa


def cpu_places(device_count=None):
    """reference framework.py:cpu_places."""
    from ..device import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """reference framework.py:cuda_places — maps to accelerator devices."""
    from ..device import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def _current_expected_place():
    from ..device import get_device
    return get_device()


def require_version(min_version, max_version=None):
    """reference framework.py:require_version — always satisfied (this
    framework replaces the versioned C++ core)."""


# --- FLAGS registry (reference framework.py:set_flags/get_flags) ------------
# The reference's FLAGS_* are gflags read by the C++ core. Here a python
# registry holds the values; flags with a live analogue apply a mapping
# (everything else is stored + readable, so config code round-trips).
_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_use_mkldnn": False,
}


def set_flags(flags):
    """reference framework.py:set_flags."""
    if not isinstance(flags, dict):
        raise TypeError("flags in set_flags should be a dict")
    for k, v in flags.items():
        _FLAGS[k] = v
        if k == "FLAGS_check_nan_inf":
            import jax as _jax
            _jax.config.update("jax_debug_nans", bool(v))


def get_flags(flags):
    """reference framework.py:get_flags — accepts a name or a
    list/tuple of names; returns {name: value}."""
    if isinstance(flags, str):
        flags = [flags]
    if not isinstance(flags, (list, tuple)):
        raise TypeError(
            "Flags in get_flags should be a list, tuple or string.")
    out = {}
    for k in flags:
        if k not in _FLAGS:
            raise ValueError(
                f"Flag {k} cannot get its value through this function.")
        out[k] = _FLAGS[k]
    return out


def load_op_library(lib_filename):
    """reference framework.py:load_op_library — loads a custom-op .so
    built against the CUDA/C++ core. That ABI does not exist here;
    custom ops are jax-traceable python (paddle_tpu.dispatch.apply) or
    Pallas kernels (paddle_tpu.ops.pallas), so loading a CUDA op
    library is an explicit error, not a silent no-op."""
    raise RuntimeError(
        f"load_op_library({lib_filename!r}): CUDA custom-op libraries "
        "target the reference's C++ core. Register custom ops as "
        "jax-traceable functions (paddle_tpu.dispatch.apply) or Pallas "
        "kernels (paddle_tpu.ops.pallas) instead.")


# structural aliases: the Program redesign keeps Block/Operator as the
# graph-node classes inside static/__init__.py
from ..static import Block  # noqa: F401,E402
from ..static import OpNode as Operator  # noqa: F401,E402


# --- remaining internals parity ---------------------------------------------
import contextlib as _ctx

ParamBase = Parameter            # dygraph-era parameter class name
ComplexVariable = Tensor         # complex support rides jnp complex dtypes
VariableMetaClass = type
ParameterMetaClass = type


class NameScope:
    """reference framework.py:NameScope tree (name_scope() is the user
    API; this mirrors the node type)."""

    def __init__(self, name="", parent=None):
        self._name = name
        self._parent = parent
        self._children = {}

    def child(self, prefix):
        node = NameScope(prefix, self)
        self._children.setdefault(prefix, []).append(node)
        return node

    def parent(self):
        return self._parent

    def name(self):
        return self._name


class OpProtoHolder:
    """reference framework.py:OpProtoHolder — op registry facade over the
    dispatch table (no protobuf protos in the rebuild)."""

    _instance = None

    @classmethod
    def instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def get_op_proto(self, type_name):
        raise KeyError(
            f"no protobuf proto for '{type_name}': ops lower straight to "
            "XLA here (see paddle_tpu.dispatch)")


def cuda_pinned_places(device_count=None):
    """reference framework.py:cuda_pinned_places — host staging memory is
    the csrc arena; returns CPU places for parity."""
    return cpu_places(device_count)


@_ctx.contextmanager
def device_guard(device=None):
    """reference framework.py:device_guard — pins ops to a device inside
    the block. Maps to jax.default_device."""
    import jax as _jax
    if device is None:
        yield
        return
    try:
        plat = {"cpu": "cpu", "gpu": "tpu", "tpu": "tpu",
                "cuda": "tpu"}.get(str(device).split(":")[0], None)
        dev = _jax.devices(plat)[0] if plat else None
    except Exception:
        dev = None
    if dev is None:
        yield
    else:
        with _jax.default_device(dev):
            yield


class _IrStub:
    """reference framework.py IrGraph/IrNode family — the SSA graph-pass
    API has no analogue (XLA owns graph optimization); constructing one is
    an explicit error rather than a silent shim."""

    def __init__(self, *a, **kw):
        raise RuntimeError(
            f"{type(self).__name__} is the C++ IR graph-pass API; XLA "
            "performs graph optimization in this framework (jit/to_static)")


class IrGraph(_IrStub):
    pass


class IrNode(_IrStub):
    pass


class IrOpNode(_IrStub):
    pass


class IrVarNode(_IrStub):
    pass
