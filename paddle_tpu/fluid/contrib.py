"""fluid.contrib facade — mixed precision + slim quantization.

Rebuild of the reference contrib surface the book/benchmarks use
(reference: python/paddle/fluid/contrib/mixed_precision/decorator.py
:decorate, fp16_lists.py AutoMixedPrecisionLists; contrib/slim/
quantization → paddle_tpu.quantization). The heavy machinery lives in
paddle_tpu.amp / paddle_tpu.quantization; these names make ported fluid
code resolve.
"""
from __future__ import annotations

import types

from .. import amp as _amp
from .. import quantization as _quantization


class AutoMixedPrecisionLists:
    """reference: fp16_lists.py — white/black op lists. The bf16 policy
    in paddle_tpu.amp white-lists matmul/conv by construction; these
    lists are carried for API parity and future policy overrides."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


class _DecoratedOptimizer:
    """reference: decorator.py:OptimizerWithMixedPrecision — wraps an
    optimizer so minimize() runs under auto_cast with loss scaling."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
                 use_dynamic_loss_scaling=True, **kw):
        self._opt = optimizer
        self._scaler = _amp.GradScaler(
            enable=use_dynamic_loss_scaling,
            init_loss_scaling=init_loss_scaling)
        self.amp_lists = amp_lists

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def backward(self, loss, **kw):
        if self._scaler is not None:
            loss = self._scaler.scale(loss)
        loss.backward()
        return []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._scaler is not None:
            scaled = self._scaler.scale(loss)
            scaled.backward()
            self._scaler.step(self._opt)
            self._scaler.update()
            self._opt.clear_grad()
            return [], []
        return self._opt.minimize(loss)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """reference: mixed_precision/decorator.py:decorate."""
    return _DecoratedOptimizer(
        optimizer, amp_lists, init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling)


mixed_precision = types.SimpleNamespace(
    decorate=decorate,
    AutoMixedPrecisionLists=AutoMixedPrecisionLists,
)

slim = types.SimpleNamespace(quantization=_quantization)
quantize = _quantization
