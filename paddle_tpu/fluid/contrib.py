"""fluid.contrib facade — mixed precision + slim quantization.

Rebuild of the reference contrib surface the book/benchmarks use
(reference: python/paddle/fluid/contrib/mixed_precision/decorator.py
:decorate, fp16_lists.py AutoMixedPrecisionLists; contrib/slim/
quantization → paddle_tpu.quantization). The heavy machinery lives in
paddle_tpu.amp / paddle_tpu.quantization; these names make ported fluid
code resolve.
"""
from __future__ import annotations

import types

from .. import amp as _amp
from .. import quantization as _quantization


class AutoMixedPrecisionLists:
    """reference: fp16_lists.py — white/black op lists. The bf16 policy
    in paddle_tpu.amp white-lists matmul/conv by construction; these
    lists are carried for API parity and future policy overrides."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


class _DecoratedOptimizer:
    """reference: decorator.py:OptimizerWithMixedPrecision — wraps an
    optimizer so minimize() runs under auto_cast with loss scaling."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
                 use_dynamic_loss_scaling=True, **kw):
        self._opt = optimizer
        self._scaler = _amp.GradScaler(
            enable=use_dynamic_loss_scaling,
            init_loss_scaling=init_loss_scaling)
        self.amp_lists = amp_lists

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def backward(self, loss, **kw):
        if self._scaler is not None:
            loss = self._scaler.scale(loss)
        loss.backward()
        return []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._scaler is not None:
            scaled = self._scaler.scale(loss)
            scaled.backward()
            self._scaler.step(self._opt)
            self._scaler.update()
            self._opt.clear_grad()
            return [], []
        return self._opt.minimize(loss)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """reference: mixed_precision/decorator.py:decorate."""
    return _DecoratedOptimizer(
        optimizer, amp_lists, init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling)


mixed_precision = types.SimpleNamespace(
    decorate=decorate,
    AutoMixedPrecisionLists=AutoMixedPrecisionLists,
)

from ..slim import prune as _prune          # noqa: E402
from ..slim import distill as _distillation  # noqa: E402
from ..slim import nas as _nas               # noqa: E402
from ..slim import core as _slim_core        # noqa: E402

slim = types.SimpleNamespace(quantization=_quantization,
                             prune=_prune,
                             distillation=_distillation,
                             nas=_nas,
                             core=_slim_core)
quantize = _quantization


# --- contrib.layers + utility submodules (reference: contrib/__init__.py
# star-exports every submodule) ---------------------------------------------

from . import contrib_layers as layers  # noqa: E402
from .contrib_layers import (  # noqa: F401,E402
    fused_elemwise_activation, shuffle_batch, partial_concat, partial_sum,
    batch_fc, match_matrix_tensor, sequence_topk_avg_pooling, var_conv_2d,
    fused_embedding_seq_pool, multiclass_nms2, tree_conv,
    search_pyramid_hash, rank_attention, tdm_child, tdm_sampler,
    basic_gru, basic_lstm, BasicGRUUnit, BasicLSTMUnit, ctr_metric_bundle)


def extend_with_decoupled_weight_decay(base_optimizer):
    """reference contrib/extend_optimizer: returns a subclass of
    base_optimizer whose minimize applies DECOUPLED weight decay
    (p -= lr*coeff*p after the base update) — the AdamW construction."""
    class DecoupledWeightDecay(base_optimizer):
        def __init__(self, weight_decay=0.0, *args, **kw):
            self._decoupled_wd = float(weight_decay) if not hasattr(
                weight_decay, "coeff") else weight_decay.coeff
            super().__init__(*args, **kw)

        def _rule(self, p, g, slots, lr):
            new_p, new_slots = super()._rule(p, g, slots, lr)
            new_p = new_p - lr * self._decoupled_wd * p
            return new_p, new_slots

    DecoupledWeightDecay.__name__ = (
        base_optimizer.__name__ + "DecoupledWeightDecay")
    return DecoupledWeightDecay


def op_freq_statistic(program, *example_args):
    """reference contrib/op_frequence.py:op_freq_statistic — (uni, pair)
    op-type frequency counters over the recorded graph. Also accepts a
    CALLABLE + example args: counts primitive names in the traced jaxpr
    (the op stream XLA actually compiles) — contrib_tools.py."""
    from collections import Counter, OrderedDict
    if callable(program) and not hasattr(program, "blocks"):
        from .contrib_tools import op_freq_statistic as _jaxpr_freq
        return _jaxpr_freq(program, *example_args)
    uni = Counter()
    adj = Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type or "unknown"] += 1
            if prev is not None:
                adj[f"{prev}->{op.type}"] += 1
            prev = op.type
    return (OrderedDict(uni.most_common()), OrderedDict(adj.most_common()))


def memory_usage(program, batch_size=1):
    """reference contrib/memory_usage_calc.py:memory_usage — lower/upper
    estimate (MB) from the program's var shapes with None/-1 dims filled
    by batch_size. Also accepts an nn.Layer (params+grads .. +adam-slot
    band — contrib_tools.py)."""
    import numpy as _np
    from ..nn.layer import Layer as _Layer
    if isinstance(program, _Layer):
        from .contrib_tools import memory_usage as _layer_mem
        return _layer_mem(program, batch_size)
    total = 0.0
    for block in program.blocks:
        for var in block.vars.values():
            shape = getattr(var, "shape", None)
            if not shape:
                continue
            n = 1
            for d in shape:
                n *= batch_size if (d is None or d < 0) else d
            dt = str(getattr(var, "dtype", "float32"))
            total += n * _np.dtype(dt if dt != "bfloat16" else "u2"
                                   ).itemsize
    for name, p in program.param_vars.items():
        total += p.data.nbytes  # metadata only — no device-to-host copy
    mb = total / (1 << 20)
    return mb * 0.9, mb * 1.1


def summary(main_prog, input_spec=None, input=None):
    """reference contrib/model_stat.py:summary — PARAMs/FLOPs table over
    the recorded static program; returns the table string (and prints).
    Also accepts an nn.Layer + example input: per-layer shape/param/FLOPs
    table via capture hooks (contrib_tools.py)."""
    from ..nn.layer import Layer as _Layer
    if isinstance(main_prog, _Layer):
        from .contrib_tools import summary as _layer_summary
        return _layer_summary(main_prog, input_spec=input_spec,
                              input=input)
    rows = []
    total_params = 0
    for name, p in main_prog.param_vars.items():
        n = int(p.data.size)  # metadata only — no device-to-host copy
        total_params += n
        rows.append((name, tuple(p.data.shape), n))
    lines = ["%-40s %-20s %12s" % ("param", "shape", "count"),
             "-" * 74]
    for r in rows:
        lines.append("%-40s %-20s %12d" % (r[0], str(r[1]), r[2]))
    lines.append("-" * 74)
    op_counts, _ = op_freq_statistic(main_prog)
    lines.append(f"total params: {total_params:,}")
    lines.append("ops: " + ", ".join(f"{k}x{v}"
                                     for k, v in list(op_counts.items())[:12]))
    table = "\n".join(lines)
    print(table)
    return table


model_stat = types.SimpleNamespace(summary=summary)
memory_usage_calc = types.SimpleNamespace(memory_usage=memory_usage)
op_frequence = types.SimpleNamespace(op_freq_statistic=op_freq_statistic)
extend_optimizer = types.SimpleNamespace(
    extend_with_decoupled_weight_decay=extend_with_decoupled_weight_decay)


# --- contrib.decoder / contrib.reader / contrib.utils -----------------------

def distributed_batch_reader(batch_reader):
    """reference contrib/reader/distributed_reader.py — shard a batch
    reader across trainers (env PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM,
    same contract as the reference)."""
    import os as _os

    def _impl():
        rank = int(_os.environ.get("PADDLE_TRAINER_ID", 0))
        world = int(_os.environ.get("PADDLE_TRAINERS_NUM", 1))
        for i, batch in enumerate(batch_reader()):
            if i % world == rank:
                yield batch

    return _impl


class InitState:
    """reference contrib/decoder/beam_search_decoder.py:InitState."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        self.init = init if init is not None else init_boot
        self.shape = shape
        self.value = value
        self.dtype = dtype


class StateCell:
    """reference contrib/decoder:StateCell — named-state step cell. The
    redesign keeps the dict-of-states + compute_state/update_states
    protocol; the heavy lifting (beam bookkeeping) lives in
    nn.decode.BeamSearchDecoder."""

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs or {})
        self._states = {}
        for k, v in (states or {}).items():
            init = getattr(v, "init", v)
            if init is None and getattr(v, "shape", None) is not None:
                import numpy as _np
                from ..tensor import Tensor as _T
                init = _T(_np.full(tuple(v.shape), v.value,
                                   dtype=v.dtype))
            self._states[k] = init
        self._out_state = out_state
        self._updater = None

    def state_updater(self, fn):
        self._updater = fn
        return fn

    def get_state(self, name):
        return self._states[name]

    def set_state(self, name, value):
        self._states[name] = value

    def get_input(self, name):
        return self._inputs[name]

    def set_input(self, name, value):
        self._inputs[name] = value

    def compute_state(self, inputs):
        self._inputs.update(inputs)
        if self._updater is not None:
            self._updater(self)

    def update_states(self):
        pass  # states already updated in-place by the updater

    def out_state(self):
        return self._states[self._out_state]


class TrainingDecoder:
    """reference contrib/decoder:TrainingDecoder — teacher-forced decode
    loop over a StateCell (padded redesign: python loop over T under
    trace, one fused computation under to_static)."""

    def __init__(self, state_cell, name=None):
        self.state_cell = state_cell
        self._outputs = []   # list of per-step tuples

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def g():
            yield self
        return g()

    def step_input(self, x):
        return x

    def static_input(self, x):
        return x

    def output(self, *outputs):
        self._outputs.append(tuple(outputs))

    def __call__(self):
        from .. import ops as _ops
        n_streams = len(self._outputs[0])
        stacked = tuple(
            _ops.stack([step[i] for step in self._outputs], axis=1)
            if len(self._outputs) > 1 else self._outputs[0][i]
            for i in range(n_streams))
        return stacked[0] if n_streams == 1 else stacked


from ..nn.decode import BeamSearchDecoder as _NNBeam  # noqa: E402


class ContribBeamSearchDecoder(_NNBeam):
    """reference contrib/decoder:BeamSearchDecoder — same algorithm as
    nn.decode.BeamSearchDecoder (gather/top-k over a [batch, beam]
    lattice); alias with the contrib name."""


decoder = types.SimpleNamespace(
    InitState=InitState, StateCell=StateCell,
    TrainingDecoder=TrainingDecoder,
    BeamSearchDecoder=ContribBeamSearchDecoder)
reader = types.SimpleNamespace(
    distributed_batch_reader=distributed_batch_reader)


def _hdfs_stub(name):
    def f(*a, **kw):
        raise RuntimeError(
            f"contrib.utils.{name}: HDFS access is environment-specific "
            "(reference contrib/utils/hdfs_utils.py shells out to the "
            "hadoop CLI); wire your storage into io.DataLoader/dataset "
            "readers instead")
    f.__name__ = name
    return f


utils = types.SimpleNamespace(
    HDFSClient=_hdfs_stub("HDFSClient"),
    multi_download=_hdfs_stub("multi_download"),
    multi_upload=_hdfs_stub("multi_upload"),
)
