"""fluid.contrib facade — mixed precision + slim quantization.

Rebuild of the reference contrib surface the book/benchmarks use
(reference: python/paddle/fluid/contrib/mixed_precision/decorator.py
:decorate, fp16_lists.py AutoMixedPrecisionLists; contrib/slim/
quantization → paddle_tpu.quantization). The heavy machinery lives in
paddle_tpu.amp / paddle_tpu.quantization; these names make ported fluid
code resolve.
"""
from __future__ import annotations

import types

from .. import amp as _amp
from .. import quantization as _quantization


class AutoMixedPrecisionLists:
    """reference: fp16_lists.py — white/black op lists. The bf16 policy
    in paddle_tpu.amp white-lists matmul/conv by construction; these
    lists are carried for API parity and future policy overrides."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


class _DecoratedOptimizer:
    """reference: decorator.py:OptimizerWithMixedPrecision — wraps an
    optimizer so minimize() runs under auto_cast with loss scaling."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
                 use_dynamic_loss_scaling=True, **kw):
        self._opt = optimizer
        self._scaler = _amp.GradScaler(
            enable=use_dynamic_loss_scaling,
            init_loss_scaling=init_loss_scaling)
        self.amp_lists = amp_lists

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def backward(self, loss, **kw):
        if self._scaler is not None:
            loss = self._scaler.scale(loss)
        loss.backward()
        return []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._scaler is not None:
            scaled = self._scaler.scale(loss)
            scaled.backward()
            self._scaler.step(self._opt)
            self._scaler.update()
            self._opt.clear_grad()
            return [], []
        return self._opt.minimize(loss)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """reference: mixed_precision/decorator.py:decorate."""
    return _DecoratedOptimizer(
        optimizer, amp_lists, init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling)


mixed_precision = types.SimpleNamespace(
    decorate=decorate,
    AutoMixedPrecisionLists=AutoMixedPrecisionLists,
)

slim = types.SimpleNamespace(quantization=_quantization)
quantize = _quantization


# --- contrib.layers + utility submodules (reference: contrib/__init__.py
# star-exports every submodule) ---------------------------------------------

from . import contrib_layers as layers  # noqa: E402
from .contrib_layers import (  # noqa: F401,E402
    fused_elemwise_activation, shuffle_batch, partial_concat, partial_sum,
    batch_fc, match_matrix_tensor, sequence_topk_avg_pooling, var_conv_2d,
    fused_embedding_seq_pool, multiclass_nms2, tree_conv,
    search_pyramid_hash, rank_attention, tdm_child, tdm_sampler,
    basic_gru, basic_lstm, BasicGRUUnit, BasicLSTMUnit, ctr_metric_bundle)


def extend_with_decoupled_weight_decay(base_optimizer):
    """reference contrib/extend_optimizer: returns a subclass of
    base_optimizer whose minimize applies DECOUPLED weight decay
    (p -= lr*coeff*p after the base update) — the AdamW construction."""
    class DecoupledWeightDecay(base_optimizer):
        def __init__(self, weight_decay=0.0, *args, **kw):
            self._decoupled_wd = float(weight_decay) if not hasattr(
                weight_decay, "coeff") else weight_decay.coeff
            super().__init__(*args, **kw)

        def _rule(self, p, g, slots, lr):
            new_p, new_slots = super()._rule(p, g, slots, lr)
            new_p = new_p - lr * self._decoupled_wd * p
            return new_p, new_slots

    DecoupledWeightDecay.__name__ = (
        base_optimizer.__name__ + "DecoupledWeightDecay")
    return DecoupledWeightDecay


def op_freq_statistic(program):
    """reference contrib/op_frequence.py:op_freq_statistic — (uni, pair)
    op-type frequency counters over the recorded graph."""
    from collections import Counter, OrderedDict
    uni = Counter()
    adj = Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type or "unknown"] += 1
            if prev is not None:
                adj[f"{prev}->{op.type}"] += 1
            prev = op.type
    return (OrderedDict(uni.most_common()), OrderedDict(adj.most_common()))


def memory_usage(program, batch_size=1):
    """reference contrib/memory_usage_calc.py:memory_usage — lower/upper
    estimate (MB) from the program's var shapes with None/-1 dims filled
    by batch_size."""
    import numpy as _np
    total = 0.0
    for block in program.blocks:
        for var in block.vars.values():
            shape = getattr(var, "shape", None)
            if not shape:
                continue
            n = 1
            for d in shape:
                n *= batch_size if (d is None or d < 0) else d
            dt = str(getattr(var, "dtype", "float32"))
            total += n * _np.dtype(dt if dt != "bfloat16" else "u2"
                                   ).itemsize
    for name, p in program.param_vars.items():
        total += p.data.nbytes  # metadata only — no device-to-host copy
    mb = total / (1 << 20)
    return mb * 0.9, mb * 1.1


def summary(main_prog):
    """reference contrib/model_stat.py:summary — PARAMs/FLOPs table over
    the recorded static program; returns the table string (and prints)."""
    rows = []
    total_params = 0
    for name, p in main_prog.param_vars.items():
        n = int(p.data.size)  # metadata only — no device-to-host copy
        total_params += n
        rows.append((name, tuple(p.data.shape), n))
    lines = ["%-40s %-20s %12s" % ("param", "shape", "count"),
             "-" * 74]
    for r in rows:
        lines.append("%-40s %-20s %12d" % (r[0], str(r[1]), r[2]))
    lines.append("-" * 74)
    op_counts, _ = op_freq_statistic(main_prog)
    lines.append(f"total params: {total_params:,}")
    lines.append("ops: " + ", ".join(f"{k}x{v}"
                                     for k, v in list(op_counts.items())[:12]))
    table = "\n".join(lines)
    print(table)
    return table


model_stat = types.SimpleNamespace(summary=summary)
memory_usage_calc = types.SimpleNamespace(memory_usage=memory_usage)
op_frequence = types.SimpleNamespace(op_freq_statistic=op_freq_statistic)
extend_optimizer = types.SimpleNamespace(
    extend_with_decoupled_weight_decay=extend_with_decoupled_weight_decay)
