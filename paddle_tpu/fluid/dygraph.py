"""paddle_tpu.fluid.dygraph — fluid.dygraph compatibility surface.

Mirrors reference python/paddle/fluid/dygraph/__init__.py: guard,
to_variable, Layer + the layer zoo, no_grad, TracedLayer, save/load,
DataParallel, to_static.
"""
from __future__ import annotations

import contextlib

from ..tensor import Tensor, Parameter
from ..nn import (Layer, Sequential, LayerList, ParameterList, Linear,
                  Conv2D, Conv2DTranspose, Conv3D, Pool2D, BatchNorm,
                  LayerNorm, GroupNorm, InstanceNorm2D, SpectralNorm,
                  Embedding, Dropout, PRelu, BilinearTensorProduct, GRUUnit)
from ..autograd import no_grad
from ..jit import to_static, TracedLayer
from ..io import save_dygraph, load_dygraph
from ..parallel import DataParallel
from ..parallel.env import ParallelEnv, prepare_context
from ..optimizer import lr as learning_rate_scheduler  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """reference: fluid.dygraph.guard — dygraph is this framework's
    default mode; the guard just ensures static mode is off inside."""
    from .. import static as _static
    was_static = _static.in_static_mode()
    if was_static:
        _static.disable_static()
    try:
        yield
    finally:
        if was_static:
            _static.enable_static()


def to_variable(value, name=None, zero_copy=None):
    """reference: dygraph/base.py:to_variable."""
    return Tensor(value, stop_gradient=True, name=name)


def enabled():
    from .. import static as _static
    return not _static.in_static_mode()
