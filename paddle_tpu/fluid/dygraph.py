"""paddle_tpu.fluid.dygraph — fluid.dygraph compatibility surface.

Mirrors reference python/paddle/fluid/dygraph/__init__.py: guard,
to_variable, Layer + the layer zoo, no_grad, TracedLayer, save/load,
DataParallel, to_static.
"""
from __future__ import annotations

import contextlib

from ..tensor import Tensor, Parameter
from ..nn import (Layer, Sequential, LayerList, ParameterList, Linear,
                  Conv2D, Conv2DTranspose, Conv3D, Pool2D, BatchNorm,
                  LayerNorm, GroupNorm, InstanceNorm2D, SpectralNorm,
                  Embedding, Dropout, PRelu, BilinearTensorProduct, GRUUnit)
from ..autograd import no_grad
from ..jit import to_static, TracedLayer
from ..io import save_dygraph, load_dygraph
from ..parallel import DataParallel
from ..parallel.env import ParallelEnv, prepare_context
from ..optimizer import lr as learning_rate_scheduler  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """reference: fluid.dygraph.guard — dygraph is this framework's
    default mode; the guard just ensures static mode is off inside."""
    from .. import static as _static
    was_static = _static.in_static_mode()
    if was_static:
        _static.disable_static()
    try:
        yield
    finally:
        if was_static:
            _static.enable_static()


def to_variable(value, name=None, zero_copy=None):
    """reference: dygraph/base.py:to_variable."""
    return Tensor(value, stop_gradient=True, name=name)


def enabled():
    from .. import static as _static
    return not _static.in_static_mode()


# --- remaining dygraph/nn.py + dygraph/base.py parity -----------------------

from ..nn.layers import Conv3DTranspose, TreeConv, NCE  # noqa: F401,E402
InstanceNorm = InstanceNorm2D  # fluid-era name


def enable_dygraph(place=None):
    """reference dygraph/base.py:enable_dygraph."""
    from .. import static as _static
    if _static.in_static_mode():
        _static.disable_static()


def disable_dygraph():
    from .. import static as _static
    if not _static.in_static_mode():
        _static.enable_static()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """reference dygraph/base.py:grad → tape autograd.grad."""
    from ..autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 retain_graph=bool(retain_graph))


@contextlib.contextmanager
def param_guard(parameters=None):
    """reference dygraph/base.py:param_guard — the dygraph/static param
    bridge is automatic here (Parameters are concrete either way)."""
    yield


@contextlib.contextmanager
def program_desc_tracing_guard(enable):
    """reference dygraph/base.py — no ProgramDesc tracer exists in the
    jit.to_static redesign; parity no-op."""
    yield


class RowConv(Layer):
    """Lookahead (row) convolution for streaming models (reference:
    dygraph/nn.py:2731 RowConv / row_conv_op): out[t] = sum_{j=0..C}
    x[t+j] * W[j], per feature. Padded [B, T, D] redesign of the LoD op;
    one gather-free implementation via shifted adds (C+1 terms unrolled —
    C is small in DeepSpeech-style models)."""

    def __init__(self, name_scope=None, future_context_size=2,
                 param_attr=None, act=None, input_dim=None):
        super().__init__()
        self._ctx = int(future_context_size)
        self._act = act
        self._param_attr = param_attr
        self._dim = input_dim
        self.weight = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, d):
        from .. import initializer as I
        self.weight = self.create_parameter(
            (self._ctx + 1, d), attr=self._param_attr,
            default_initializer=I.XavierUniform())
        self._dim = d

    def forward(self, x):
        if self.weight is None:
            self._build(int(x.shape[-1]))
        from ..dispatch import apply
        import jax.numpy as jnp

        def impl(x, w):
            T = x.shape[1]
            out = x * w[0]
            for j in range(1, w.shape[0]):
                shifted = jnp.pad(x[:, j:], ((0, 0), (0, j), (0, 0)))
                out = out + shifted * w[j]
            return out

        out = apply(impl, (x, self.weight), name="row_conv")
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class SequenceConv(Layer):
    """Dygraph wrapper over the padded sequence_conv op (reference:
    dygraph/nn.py SequenceConv over sequence_conv_op)."""

    def __init__(self, name_scope=None, num_filters=1, filter_size=3,
                 padding_start=None, param_attr=None, bias_attr=None,
                 act=None, input_dim=None):
        super().__init__()
        self._nf = num_filters
        self._fs = filter_size
        self._pad = padding_start
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, d):
        from .. import initializer as I
        self.weight = self.create_parameter(
            (self._fs * d, self._nf), attr=self._param_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter((self._nf,), attr=self._bias_attr,
                                          is_bias=True)

    def forward(self, x, length=None):
        if self.weight is None:
            self._build(int(x.shape[-1]))
        from ..ops.sequence import sequence_conv as _op
        out = _op(x, self.weight, self.bias, filter_size=self._fs,
                  padding_start=self._pad, length=length)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out
