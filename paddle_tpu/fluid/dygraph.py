"""paddle_tpu.fluid.dygraph — fluid.dygraph compatibility surface.

Mirrors reference python/paddle/fluid/dygraph/__init__.py: guard,
to_variable, Layer + the layer zoo, no_grad, TracedLayer, save/load,
DataParallel, to_static.
"""
from __future__ import annotations

import contextlib

from ..tensor import Tensor, Parameter
from ..nn import (Layer, Sequential, LayerList, ParameterList, Linear,
                  Conv2D, Conv2DTranspose, Conv3D, Pool2D, BatchNorm,
                  LayerNorm, GroupNorm, InstanceNorm2D, SpectralNorm,
                  Embedding, Dropout, PRelu, BilinearTensorProduct, GRUUnit)
from ..autograd import no_grad
from ..jit import to_static, TracedLayer
from ..dygraph_to_static import ProgramTranslator  # noqa: F401
from ..io import save_dygraph, load_dygraph
from ..parallel import DataParallel
from ..parallel.env import ParallelEnv, prepare_context
# The 1.x dygraph decay classes live in dygraph_lr (distinct protocol
# from optimizer.lr's 2.x LRScheduler — see that module's docstring).
from . import dygraph_lr as learning_rate_scheduler  # noqa: F401
from .dygraph_lr import (LearningRateDecay, NoamDecay,  # noqa: F401
                         PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
                         InverseTimeDecay, PolynomialDecay, CosineDecay,
                         LinearLrWarmup)


@contextlib.contextmanager
def guard(place=None):
    """reference: fluid.dygraph.guard — dygraph is this framework's
    default mode; the guard just ensures static mode is off inside."""
    from .. import static as _static
    was_static = _static.in_static_mode()
    if was_static:
        _static.disable_static()
    try:
        yield
    finally:
        if was_static:
            _static.enable_static()


def to_variable(value, name=None, zero_copy=None):
    """reference: dygraph/base.py:to_variable."""
    return Tensor(value, stop_gradient=True, name=name)


def enabled():
    from .. import static as _static
    return not _static.in_static_mode()


class BackwardStrategy:
    """reference dygraph/backward_strategy.py:BackwardStrategy —
    sort_sum_gradient has no effect here (the tape sums in deterministic
    order already). paddle_tpu.imperative re-exports this."""

    def __init__(self):
        self.sort_sum_gradient = False


# --- remaining dygraph/nn.py + dygraph/base.py parity -----------------------

from ..nn.layers import Conv3DTranspose, TreeConv, NCE  # noqa: F401,E402
InstanceNorm = InstanceNorm2D  # fluid-era name


def enable_dygraph(place=None):
    """reference dygraph/base.py:enable_dygraph."""
    from .. import static as _static
    if _static.in_static_mode():
        _static.disable_static()


def disable_dygraph():
    from .. import static as _static
    if not _static.in_static_mode():
        _static.enable_static()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """reference dygraph/base.py:grad → tape autograd.grad."""
    from ..autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 retain_graph=bool(retain_graph))


@contextlib.contextmanager
def param_guard(parameters=None):
    """reference dygraph/base.py:param_guard — the dygraph/static param
    bridge is automatic here (Parameters are concrete either way)."""
    yield


@contextlib.contextmanager
def program_desc_tracing_guard(enable):
    """reference dygraph/base.py — no ProgramDesc tracer exists in the
    jit.to_static redesign; parity no-op."""
    yield


class RowConv(Layer):
    """Lookahead (row) convolution for streaming models (reference:
    dygraph/nn.py:2731 RowConv / row_conv_op): out[t] = sum_{j=0..C}
    x[t+j] * W[j], per feature. Padded [B, T, D] redesign of the LoD op;
    one gather-free implementation via shifted adds (C+1 terms unrolled —
    C is small in DeepSpeech-style models)."""

    def __init__(self, name_scope=None, future_context_size=2,
                 param_attr=None, act=None, input_dim=None):
        super().__init__()
        self._ctx = int(future_context_size)
        self._act = act
        self._param_attr = param_attr
        self._dim = input_dim
        self.weight = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, d):
        from .. import initializer as I
        self.weight = self.create_parameter(
            (self._ctx + 1, d), attr=self._param_attr,
            default_initializer=I.XavierUniform())
        self._dim = d

    def forward(self, x):
        if self.weight is None:
            self._build(int(x.shape[-1]))
        from ..dispatch import apply
        import jax.numpy as jnp

        def impl(x, w):
            T = x.shape[1]
            out = x * w[0]
            for j in range(1, w.shape[0]):
                shifted = jnp.pad(x[:, j:], ((0, 0), (0, j), (0, 0)))
                out = out + shifted * w[j]
            return out

        out = apply(impl, (x, self.weight), name="row_conv")
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class SequenceConv(Layer):
    """Dygraph wrapper over the padded sequence_conv op (reference:
    dygraph/nn.py SequenceConv over sequence_conv_op)."""

    def __init__(self, name_scope=None, num_filters=1, filter_size=3,
                 padding_start=None, param_attr=None, bias_attr=None,
                 act=None, input_dim=None):
        super().__init__()
        self._nf = num_filters
        self._fs = filter_size
        self._pad = padding_start
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, d):
        from .. import initializer as I
        self.weight = self.create_parameter(
            (self._fs * d, self._nf), attr=self._param_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter((self._nf,), attr=self._bias_attr,
                                          is_bias=True)

    def forward(self, x, length=None):
        if self.weight is None:
            self._build(int(x.shape[-1]))
        from ..ops.sequence import sequence_conv as _op
        out = _op(x, self.weight, self.bias, filter_size=self._fs,
                  padding_start=self._pad, length=length)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


# --- dygraph/rnn.py parity: legacy-signature cells ---------------------------

class LSTMCell(Layer):
    """reference dygraph/rnn.py:LSTMCell — the 1.x dygraph cell with
    (hidden_size, input_size) argument order and a CUDNN-layout default
    (separate ih/hh weights, i,f,c,o gate chunks) plus the basic
    fused-weight variant (use_cudnn_impl=False, i,j,f,o chunks with
    forget_bias). Distinct from paddle_tpu.nn.LSTMCell (2.x signature).
    dtype follows TPU canonicalization (f64 requests run as f32)."""

    def __init__(self, hidden_size, input_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, use_cudnn_impl=True, dtype="float32"):
        super().__init__()
        import jax.numpy as jnp
        from ..nn import functional as F
        from ..ops.math import tanh as _tanh
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gate_activation = gate_activation or F.sigmoid
        self._activation = activation or _tanh
        self._use_cudnn_impl = use_cudnn_impl
        if use_cudnn_impl:
            self._weight_ih = self.create_parameter(
                (4 * hidden_size, input_size), attr=param_attr, dtype=dtype)
            self._weight_hh = self.create_parameter(
                (4 * hidden_size, hidden_size), attr=param_attr, dtype=dtype)
            self._bias_ih = self.create_parameter(
                (4 * hidden_size,), attr=bias_attr, dtype=dtype, is_bias=True)
            self._bias_hh = self.create_parameter(
                (4 * hidden_size,), attr=bias_attr, dtype=dtype, is_bias=True)
        else:
            self._forget_bias = float(forget_bias)
            self._weight = self.create_parameter(
                (input_size + hidden_size, 4 * hidden_size),
                attr=param_attr, dtype=dtype)
            self._bias = self.create_parameter(
                (4 * hidden_size,), attr=bias_attr, dtype=dtype,
                is_bias=True)

    def forward(self, input, pre_hidden, pre_cell):
        # Tensor-level ops so custom gate activations (which take
        # Tensors, like the reference's layer fns take Variables)
        # compose and the tape differentiates through them.
        import paddle_tpu as pt
        from ..nn import functional as F
        from ..ops.math import tanh
        if self._use_cudnn_impl:
            ig = pt.matmul(input, self._weight_ih, transpose_y=True) \
                + self._bias_ih
            hg = pt.matmul(pre_hidden, self._weight_hh, transpose_y=True) \
                + self._bias_hh
            ci = pt.split(ig, 4, axis=1)
            ch = pt.split(hg, 4, axis=1)
            i = self._gate_activation(ci[0] + ch[0])
            f = self._gate_activation(ci[1] + ch[1])
            g = self._activation(ci[2] + ch[2])
            o = self._gate_activation(ci[3] + ch[3])
            new_c = f * pre_cell + i * g
            new_h = o * self._activation(new_c)
        else:
            gate = pt.matmul(pt.concat([input, pre_hidden], 1),
                             self._weight) + self._bias
            i, j, f, o = pt.split(gate, 4, axis=-1)
            new_c = pre_cell * self._gate_activation(
                f + self._forget_bias) + F.sigmoid(i) * tanh(j)
            new_h = self._activation(new_c) * self._gate_activation(o)
        return new_h, new_c


class GRUCell(Layer):
    """reference dygraph/rnn.py:GRUCell — 1.x dygraph cell,
    (hidden_size, input_size) order; CUDNN layout by default (r,u,c
    chunks with reset applied to the hh candidate chunk), or the
    BasicGRUUnit fused-weight variant (use_cudnn_impl=False)."""

    def __init__(self, hidden_size, input_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 use_cudnn_impl=True, dtype="float32"):
        super().__init__()
        from ..nn import functional as F
        from ..ops.math import tanh as _tanh
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gate_activation = gate_activation or F.sigmoid
        self._activation = activation or _tanh
        self._use_cudnn_impl = use_cudnn_impl
        if use_cudnn_impl:
            self._weight_ih = self.create_parameter(
                (3 * hidden_size, input_size), attr=param_attr, dtype=dtype)
            self._weight_hh = self.create_parameter(
                (3 * hidden_size, hidden_size), attr=param_attr, dtype=dtype)
            self._bias_ih = self.create_parameter(
                (3 * hidden_size,), attr=bias_attr, dtype=dtype, is_bias=True)
            self._bias_hh = self.create_parameter(
                (3 * hidden_size,), attr=bias_attr, dtype=dtype, is_bias=True)
        else:
            self._gate_weight = self.create_parameter(
                (input_size + hidden_size, 2 * hidden_size),
                attr=param_attr, dtype=dtype)
            self._candidate_weight = self.create_parameter(
                (input_size + hidden_size, hidden_size),
                attr=param_attr, dtype=dtype)
            self._gate_bias = self.create_parameter(
                (2 * hidden_size,), attr=bias_attr, dtype=dtype,
                is_bias=True)
            self._candidate_bias = self.create_parameter(
                (hidden_size,), attr=bias_attr, dtype=dtype, is_bias=True)

    def forward(self, input, pre_hidden):
        import paddle_tpu as pt
        if self._use_cudnn_impl:
            ig = pt.matmul(input, self._weight_ih, transpose_y=True) \
                + self._bias_ih
            hg = pt.matmul(pre_hidden, self._weight_hh, transpose_y=True) \
                + self._bias_hh
            ir, iu, ic = pt.split(ig, 3, axis=1)
            hr, hu, hc = pt.split(hg, 3, axis=1)
            r = self._gate_activation(ir + hr)
            u = self._gate_activation(iu + hu)
            cand = self._activation(ic + r * hc)
            new_h = (pre_hidden - cand) * u + cand
        else:
            gate = self._gate_activation(
                pt.matmul(pt.concat([input, pre_hidden], 1),
                          self._gate_weight) + self._gate_bias)
            r, u = pt.split(gate, 2, axis=1)
            cand = self._activation(
                pt.matmul(pt.concat([input, r * pre_hidden], 1),
                          self._candidate_weight) + self._candidate_bias)
            new_h = u * pre_hidden + (1 - u) * cand
        return new_h


# --- dygraph/jit.py parity ---------------------------------------------------

def declarative(function=None, input_spec=None):
    """reference dygraph/jit.py:declarative — decorator converting a
    dygraph function to a compiled static one (alias era of
    jit.to_static)."""
    return to_static(function, input_spec=input_spec)


def dygraph_to_static_func(dygraph_func):
    """reference dygraph/jit.py:dygraph_to_static_func — converts
    imperative code for use while building a static Program. Here the
    same AST conversion that backs to_static handles both uses."""
    return to_static(dygraph_func)


# --- dygraph/profiler.py parity ----------------------------------------------

def start_gperf_profiler():
    """reference dygraph/profiler.py:start_gperf_profiler (gperftools) —
    mapped to the jax trace profiler."""
    from ..utils.profiler import start_profiler
    start_profiler()


def stop_gperf_profiler():
    from ..utils.profiler import stop_profiler
    stop_profiler()
