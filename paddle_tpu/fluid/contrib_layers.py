"""fluid.contrib.layers — the contrib op surface.

TPU-native rebuild of reference python/paddle/fluid/contrib/layers/
{nn.py, rnn_impl.py, metric_op.py}. LoD inputs become padded [B, T, ...]
(+ optional lengths); everything lowers to plain jax, fusable under jit.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..dispatch import apply
from .. import ops
from .. import initializer as I
from ..tensor import Tensor


# ---------------------------------------------------------------------------
# reference contrib/layers/nn.py

def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference contrib/layers/nn.py:fused_elemwise_activation — composes
    a binary elementwise op with a unary activation (the reference needed
    a fused CUDA kernel; XLA fuses the jnp chain for free)."""
    uns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
           "tanh": jnp.tanh, "scale": lambda v: v * scale,
           "identity": lambda v: v}
    bins = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply,
            "elementwise_sub": jnp.subtract}

    def impl(x, y):
        f0, f1 = functor_list
        if f0 in bins:
            return uns[f1](bins[f0](x, y))
        return bins[f1](uns[f0](x), y)

    return apply(impl, (x, y), name="fused_elemwise_activation")


def shuffle_batch(x, seed=None):
    """reference contrib/layers/nn.py:shuffle_batch — random row permute."""
    from .. import random as prandom

    def impl(x, key):
        perm = jax.random.permutation(
            jax.random.wrap_key_data(key) if key.dtype == jnp.uint32
            else key, x.shape[0])
        return x[perm]

    return apply(impl, (x, prandom.next_key_graph()), name="shuffle_batch")


def partial_concat(input, start_index=0, length=-1):
    """reference: partial_concat — concat a column slice of each input."""
    def impl(*xs):
        outs = []
        for x in xs:
            stop = x.shape[1] if length < 0 else start_index + length
            outs.append(x[:, start_index:stop])
        return jnp.concatenate(outs, axis=1)

    return apply(impl, tuple(input), name="partial_concat")


def partial_sum(input, start_index=0, length=-1):
    """reference: partial_sum."""
    def impl(*xs):
        acc = None
        for x in xs:
            stop = x.shape[1] if length < 0 else start_index + length
            s = x[:, start_index:stop]
            acc = s if acc is None else acc + s
        return acc

    return apply(impl, tuple(input), name="partial_sum")


def batch_fc(input, param_size, param_attr=None, bias_size=None,
             bias_attr=None, act=None):
    """reference: batch_fc — per-slot fc: input [S, B, D] × w [S, D, O]."""
    from .layers import _param, _act
    w = _param(param_attr, tuple(param_size), "float32", I.XavierUniform())
    b = _param(bias_attr, tuple(bias_size), "float32", I.Constant(0.0),
               is_bias=True) if bias_size else None

    def impl(x, w, *mb):
        out = jnp.einsum("sbd,sdo->sbo", x, w)
        if mb:
            out = out + mb[0]
        return out

    args = (input, w) if b is None else (input, w, b)
    return _act(apply(impl, args, name="batch_fc"), act)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", x_len=None, y_len=None):
    """reference: match_matrix_tensor — interaction tensor for text
    matching: out[b,t,i,j] = x[b,i]·W_t·y[b,j]. Padded redesign of the
    LoD op; returns (out [B, C, Lx, Ly], tmp)."""
    from .layers import _param, _act
    D1 = x.shape[-1]
    D2 = y.shape[-1]
    w = _param(param_attr, (D1, channel_num, D2), dtype, I.XavierUniform())

    def impl(x, y, w):
        tmp = jnp.einsum("bid,dce->bice", x, w)
        out = jnp.einsum("bice,bje->bcij", tmp, y)
        return out, tmp.reshape(x.shape[0], x.shape[1], -1)

    out, tmp = apply(impl, (x, y, w), n_out=2, name="match_matrix_tensor")
    return _act(out, act), tmp


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """reference: sequence_topk_avg_pooling — for each channel of a
    [B, C, Lx, Ly] interaction map, average the top-k values per row.
    Returns [B, Lx, C*len(topks)] (padded redesign)."""
    def impl(x):
        k_max = max(topks)
        kk = min(k_max, x.shape[-1])
        top = jax.lax.top_k(x, kk)[0]          # [B, C, Lx, kk]
        feats = []
        for k in topks:
            k_eff = min(k, kk)
            feats.append(jnp.mean(top[..., :k_eff], axis=-1))  # [B, C, Lx]
        out = jnp.stack(feats, axis=-1)         # [B, C, Lx, K]
        return jnp.transpose(out, (0, 2, 1, 3)).reshape(
            x.shape[0], x.shape[2], -1)

    return apply(impl, (input,), name="sequence_topk_avg_pooling")


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32"):
    """reference: var_conv_2d — conv over variable-size feature maps; the
    padded redesign runs one dense conv and relies on masked inputs (zero
    padding) like every other padded op here."""
    from .layers import _param, _act
    from ..ops.nn_ops import conv2d as _conv
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w = _param(param_attr, (output_channel, input_channel, ks[0], ks[1]),
               dtype, I.XavierUniform())
    out = _conv(input, w, stride=stride, padding=(ks[0] // 2, ks[1] // 2))
    return _act(out, act)


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """reference: fused_embedding_seq_pool — embedding lookup + sequence
    pool in one op (one gather + one segment reduction under XLA)."""
    from .layers import _param
    w = _param(param_attr, tuple(size), dtype,
               I.Normal(0.0, 1.0 / np.sqrt(size[1])))

    def impl(ids, w):
        ids2 = ids.reshape(ids.shape[0], -1)
        emb = w[jnp.clip(ids2, 0, w.shape[0] - 1)]
        if padding_idx is not None:
            emb = jnp.where((ids2 == padding_idx)[..., None], 0.0, emb)
        if combiner == "mean":
            return jnp.mean(emb, axis=1)
        return jnp.sum(emb, axis=1)

    return apply(impl, (input, w), name="fused_embedding_seq_pool")


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """reference contrib:multiclass_nms2 — multiclass_nms that also
    returns the selected indices."""
    from ..ops.detection import multiclass_nms
    return multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold=nms_threshold,
                          normalized=normalized, nms_eta=nms_eta,
                          background_label=background_label,
                          return_index=return_index)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference contrib:tree_conv — functional form over nn.TreeConv."""
    from ..nn.layers import TreeConv as _TC
    layer = _TC(feature_size=nodes_vector.shape[-1],
                output_size=output_size, num_filters=num_filters,
                max_depth=max_depth, act=act)
    return layer(nodes_vector, edge_set)


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent, is_training, use_filter,
                        white_list_len, black_list_len, seed, lr,
                        param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype="float32"):
    """reference contrib:search_pyramid_hash — pyramid n-gram hash
    embedding: each n-gram (n = 2..pyramid_layer+1) hashes into a shared
    1-D parameter space and the pieces average. Redesign: fixed FNV-style
    integer hashing on device (no murmur C++ dep), dense [B, T] ids."""
    from .layers import _param
    table = _param(param_attr, (space_len,), dtype, I.XavierUniform())

    def impl(ids, table):
        ids2 = ids.reshape(ids.shape[0], -1).astype(jnp.uint32)
        B, T = ids2.shape
        pooled = jnp.zeros((B, num_emb), table.dtype)
        count = 0
        for n in range(2, pyramid_layer + 2):
            if T < n:
                break
            # rolling n-gram hash
            h = jnp.zeros((B, T - n + 1), jnp.uint32)
            for k in range(n):
                h = (h * jnp.uint32(16777619)) ^ ids2[:, k:T - n + 1 + k]
            # each hash addresses a num_emb-length slice of the table
            base = (h % jnp.uint32(max(space_len - num_emb, 1))
                    ).astype(jnp.int32)
            idx = base[:, :, None] + jnp.arange(num_emb)[None, None]
            pooled = pooled + jnp.sum(table[idx], axis=1)
            count += h.shape[1]
        return pooled / jnp.maximum(count, 1)

    return apply(impl, (input, table), name="search_pyramid_hash")


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr,
                   max_rank=3, max_size=0):
    """reference contrib:rank_attention (CTR): each sample has a rank id;
    its feature goes through the weight block selected by (its rank, other
    rank) pairs encoded in rank_offset [B, 1+2*max_rank]. Redesign keeps
    the published semantics: out = x @ W[sel] summed over valid pairs."""
    from .layers import _param
    w = _param(rank_param_attr, tuple(rank_param_shape), "float32",
               I.XavierUniform())

    def impl(x, ro, w):
        D = x.shape[1]
        nblk = w.shape[0] // D
        wb = w.reshape(nblk, D, -1)
        out = jnp.zeros((x.shape[0], wb.shape[-1]), x.dtype)
        valid_total = jnp.zeros((x.shape[0], 1), x.dtype)
        for k in range(max_rank):
            idx = ro[:, 1 + 2 * k]
            valid = (idx >= 0)
            blk = jnp.clip(idx, 0, nblk - 1)
            contrib = jnp.einsum("bd,bdo->bo", x, wb[blk])
            out = out + jnp.where(valid[:, None], contrib, 0.0)
            valid_total = valid_total + valid[:, None].astype(x.dtype)
        return out / jnp.maximum(valid_total, 1.0)

    return apply(impl, (input, rank_offset, w), name="rank_attention")


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """reference contrib:tdm_child — gather each node's children from the
    tree-info table: info[node] = [item_id, layer, parent, child...]."""
    from .layers import _param
    info = _param(param_attr, (node_nums, 3 + child_nums), "int32",
                  I.Constant(0))

    def impl(x, info):
        ids = x.reshape(-1).astype(jnp.int32)
        rows = info[jnp.clip(ids, 0, info.shape[0] - 1)]
        child = rows[:, 3:3 + child_nums]
        # leaf = a real child (id != 0) whose own layer field is 0
        child_layer = info[jnp.clip(child, 0, info.shape[0] - 1), 1]
        leaf_mask = ((child_layer == 0) & (child != 0)).astype(jnp.int32)
        shape = x.shape + (child_nums,)
        return child.reshape(shape), leaf_mask.reshape(shape)

    return apply(impl, (x, info), n_out=2, name="tdm_child")


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=True, seed=0,
                tree_dtype="int32", dtype="int32"):
    """reference contrib:tdm_sampler — per tree layer, emit the positive
    travel node plus N uniform negative samples from that layer."""
    from .layers import _param
    from .. import random as prandom
    n_layer = len(layer_node_num_list)
    travel = _param(tree_travel_attr, (leaf_node_num, n_layer), "int32",
                    I.Constant(0))
    layer_sizes = list(layer_node_num_list)
    total_layer_nodes = sum(layer_sizes)
    layer_tab = _param(tree_layer_attr, (total_layer_nodes,), "int32",
                       I.Constant(0))

    def impl(x, travel, layer_tab, key):
        ids = x.reshape(-1).astype(jnp.int32)
        B = ids.shape[0]
        outs, labels, masks = [], [], []
        off = 0
        k = jax.random.wrap_key_data(key) if key.dtype == jnp.uint32 \
            else key
        for li, ln in enumerate(layer_sizes):
            pos = travel[jnp.clip(ids, 0, travel.shape[0] - 1), li]
            neg_n = neg_samples_num_list[li]
            k, sub = jax.random.split(k)
            neg_ix = jax.random.randint(sub, (B, neg_n), 0, ln)
            neg = layer_tab[off + neg_ix]
            off += ln
            if output_positive:
                o = jnp.concatenate([pos[:, None], neg], axis=1)
                lab = jnp.concatenate(
                    [jnp.ones((B, 1), jnp.int32),
                     jnp.zeros((B, neg_n), jnp.int32)], axis=1)
            else:
                o, lab = neg, jnp.zeros((B, neg_n), jnp.int32)
            outs.append(o)
            labels.append(lab)
            masks.append(jnp.ones_like(lab))
        if output_list:
            return tuple(outs) + tuple(labels) + tuple(masks)
        return (jnp.concatenate(outs, 1), jnp.concatenate(labels, 1),
                jnp.concatenate(masks, 1))

    n_out = 3 * n_layer if output_list else 3
    return apply(impl, (x, travel, layer_tab, prandom.next_key_graph()),
                 n_out=n_out, name="tdm_sampler")


# ---------------------------------------------------------------------------
# reference contrib/layers/rnn_impl.py

def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """reference contrib/layers/rnn_impl.py:164 basic_gru — stacked
    (bi)GRU over the nn.GRU driver; returns (rnn_out, last_hidden)."""
    from ..nn.rnn import GRU as _GRU
    x = input if batch_first else ops.transpose(input, [1, 0, 2])
    g = _GRU(int(x.shape[-1]), hidden_size, num_layers=num_layers,
             direction="bidirect" if bidirectional else "forward")
    out, finals = g(x, initial_states=init_hidden,
                    sequence_length=sequence_length)
    # finals: per-layer h (or (h_fw, h_bw)); stack to [L*dirs, B, H]
    hs = []
    for f in finals:
        hs.extend(list(f) if isinstance(f, (tuple, list)) else [f])
    last_hidden = ops.stack(hs, axis=0)
    if not batch_first:
        out = ops.transpose(out, [1, 0, 2])
    return out, last_hidden


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, forget_bias=1.0,
               dtype="float32", name="basic_lstm"):
    """reference contrib/layers/rnn_impl.py:405 basic_lstm."""
    from ..nn.rnn import LSTM as _LSTM
    x = input if batch_first else ops.transpose(input, [1, 0, 2])
    m = _LSTM(int(x.shape[-1]), hidden_size, num_layers=num_layers,
              direction="bidirect" if bidirectional else "forward")
    states = None
    if init_hidden is not None and init_cell is not None:
        states = (init_hidden, init_cell)
    out, finals = m(x, initial_states=states,
                    sequence_length=sequence_length)
    # finals: per-layer (h, c) (or ((h,c)_fw, (h,c)_bw))
    hs, cs = [], []
    for f in finals:
        if isinstance(f[0], (tuple, list)):   # bidirectional
            for d in f:
                hs.append(d[0])
                cs.append(d[1])
        else:
            hs.append(f[0])
            cs.append(f[1])
    if not batch_first:
        out = ops.transpose(out, [1, 0, 2])
    return out, ops.stack(hs, axis=0), ops.stack(cs, axis=0)


class BasicGRUUnit:
    """reference contrib/layers/rnn_impl.py:25 — one GRU step (class
    form); thin over nn.GRUCell."""

    def __init__(self, name_scope=None, hidden_size=None, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        self._hidden = hidden_size
        self._cell = None

    def __call__(self, input, pre_hidden):
        from ..nn.rnn import GRUCell
        if self._cell is None:
            self._cell = GRUCell(int(input.shape[-1]), self._hidden)
        out, _ = self._cell(input, pre_hidden)
        return out


class BasicLSTMUnit:
    """reference contrib/layers/rnn_impl.py:699 — one LSTM step."""

    def __init__(self, name_scope=None, hidden_size=None, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        self._hidden = hidden_size
        self._cell = None

    def __call__(self, input, pre_hidden, pre_cell):
        from ..nn.rnn import LSTMCell
        if self._cell is None:
            self._cell = LSTMCell(int(input.shape[-1]), self._hidden)
        out, (h, c) = self._cell(input, (pre_hidden, pre_cell))
        return h, c


# ---------------------------------------------------------------------------
# reference contrib/layers/metric_op.py

def ctr_metric_bundle(input, label):
    """reference contrib/layers/metric_op.py:30 — returns (local_sqrerr,
    local_abserr, local_prob, local_q) accumulators for distributed CTR
    eval."""
    def impl(p, y):
        y = y.astype(p.dtype)
        sq = jnp.sum(jnp.square(p - y))
        ab = jnp.sum(jnp.abs(p - y))
        prob = jnp.sum(p)
        q = jnp.sum(y)
        return sq, ab, prob, q

    return apply(impl, (input, label), n_out=4, name="ctr_metric_bundle")
