"""fluid.trainer_factory (reference: fluid/trainer_factory.py) —
TrainerFactory plus the FetchHandler monitoring pair. The factory
itself lives in trainer_desc.py (one module owns the trainer/worker
pairing); this module adds the periodic-fetch monitor."""
import threading
import time

from .trainer_desc import TrainerFactory  # noqa: F401

__all__ = ["TrainerFactory", "FetchHandler", "FetchHandlerMonitor"]


class FetchHandler:
    """reference trainer_factory.py:FetchHandler — subclass and override
    handler(); the monitor calls it every period_secs with a dict of
    fetched values."""

    def __init__(self, var_dict=None, period_secs=60):
        if var_dict is None:
            raise ValueError("var_dict cannot be None")
        self.var_dict = var_dict
        self.period_secs = period_secs

    def handler(self, res_dict):
        for key in res_dict:
            if isinstance(res_dict[key], list):
                print(f"{key}[0]: {res_dict[key][0]}")

    @staticmethod
    def help():
        print("""
class FetchHandlerExample(FetchHandler):
    def handler(self, res_dict):
        print(res_dict["auc"])
        print("auc: {}, {}".format(res_dict["auc"], time.ctime()))

auc = Variable()
var_dict = {"auc": auc}
handler = FetchHandlerExample(var_dict=var_dict)
""")


class FetchHandlerMonitor:
    """reference trainer_factory.py:FetchHandlerMonitor — a daemon
    thread that periodically reads the handler's variables out of a
    scope and calls handler(). Variables resolve through the scope's
    name→Tensor dict (static.Scope)."""

    def __init__(self, scope, handler):
        self.fetch_instance = handler
        self.fetch_thread = threading.Thread(
            target=self.handler_launch_func,
            args=(scope, handler), daemon=True)
        self.running_lock = threading.Lock()
        self.running = False

    def handler_launch_func(self, scope, handler):
        period = handler.period_secs
        elapsed = 0.0
        while True:
            with self.running_lock:
                if not self.running:
                    break
            if elapsed < period:
                time.sleep(1)
                elapsed += 1
                continue
            elapsed = 0.0
            res = {}
            for key, var in handler.var_dict.items():
                name = getattr(var, "name", str(var))
                found = scope.find_var(name) if scope is not None else None
                if found is None:
                    res[key] = None
                else:
                    res[key] = found.numpy() if hasattr(found, "numpy") \
                        else found
            handler.handler(res)

    def start(self):
        with self.running_lock:
            self.running = True
        self.fetch_thread.start()

    def stop(self):
        with self.running_lock:
            self.running = False
