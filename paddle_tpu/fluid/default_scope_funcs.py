"""fluid.default_scope_funcs (reference: fluid/default_scope_funcs.py)
— a thread-local stack of Scopes with enter/leave helpers. The Scope
here is the static module's name→Tensor dict (device residency is
XLA's job)."""
import threading

from ..static import Scope, global_scope

__all__ = [
    "get_cur_scope", "enter_local_scope", "leave_local_scope", "var",
    "find_var", "scoped_function",
]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "scopes"):
        _tls.scopes = [global_scope()]
    return _tls.scopes


def get_cur_scope():
    """reference default_scope_funcs.py:get_cur_scope."""
    return _stack()[-1]


def enter_local_scope():
    """Push a child scope (lookups fall back to the parent)."""
    parent = get_cur_scope()
    child = Scope()
    child._parent = parent
    _stack().append(child)
    return child


def leave_local_scope():
    if len(_stack()) == 1:
        raise RuntimeError("cannot leave the global scope")
    _stack().pop()


def var(name):
    """Get-or-create a slot for `name` in the current scope."""
    scope = get_cur_scope()
    if name not in scope.vars:
        scope.vars[name] = None
    return scope.vars[name]


def find_var(name):
    """Find `name` walking parents (reference Scope::FindVar chain)."""
    scope = get_cur_scope()
    while scope is not None:
        if name in scope.vars:
            return scope.vars[name]
        scope = getattr(scope, "_parent", None)
    return None


def scoped_function(func):
    """reference default_scope_funcs.py:scoped_function — run func
    inside a fresh local scope."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
