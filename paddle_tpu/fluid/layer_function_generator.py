"""fluid.layers.layer_function_generator (reference:
fluid/layers/layer_function_generator.py).

The reference generates layer functions from registered C++ OpProtos
(one LayerHelper append_op wrapper per proto). Ops here are plain
python functions lowering to jax, so "generation" is a registry lookup
that attaches the same doc conventions."""
import functools
import warnings

__all__ = [
    "deprecated", "generate_layer_fn", "generate_activation_fn",
    "autodoc", "templatedoc",
]


def _find_op(op_type):
    import importlib
    for modname in ("paddle_tpu.ops.math", "paddle_tpu.ops.nn_ops",
                    "paddle_tpu.ops.manip", "paddle_tpu.ops.loss",
                    "paddle_tpu.fluid.layers"):
        mod = importlib.import_module(modname)
        if hasattr(mod, op_type):
            return getattr(mod, op_type)
    return None


def generate_layer_fn(op_type):
    """reference layer_function_generator.py:generate_layer_fn — return
    the layer function for a registered op type."""
    fn = _find_op(op_type)
    if fn is None:
        raise ValueError(
            f"no op named {op_type!r} is registered (ops are python "
            "functions in paddle_tpu.ops.* / fluid.layers)")
    return fn


def generate_activation_fn(op_type):
    """reference layer_function_generator.py:generate_activation_fn."""
    from ..nn import functional as F
    if hasattr(F, op_type):
        return getattr(F, op_type)
    return generate_layer_fn(op_type)


def deprecated(func_or_class):
    """reference layer_function_generator.py:deprecated — one-shot
    DeprecationWarning wrapper."""
    @functools.wraps(func_or_class)
    def func_wrapper(*args, **kwargs):
        warnings.warn(
            f"API {func_or_class.__name__} is deprecated since 2.0.0",
            DeprecationWarning, stacklevel=2)
        return func_or_class(*args, **kwargs)
    return func_wrapper


def autodoc(comment=""):
    """reference layer_function_generator.py:autodoc — prepend a
    comment to the function's docstring."""
    def __impl__(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func
    return __impl__


def templatedoc(op_type=None):
    """reference layer_function_generator.py:templatedoc — the
    reference substitutes ${comment} placeholders from the OpProto;
    without protos this strips the placeholders so docs render clean."""
    def __impl__(func):
        doc = func.__doc__ or ""
        func.__doc__ = doc.replace("${comment}", "").strip()
        return func
    return __impl__
