"""fluid.dataset (reference: fluid/dataset.py) — the PS/CTR-era file
datasets: DatasetFactory creating QueueDataset / InMemoryDataset over a
filelist in the MultiSlot text format.

Reference architecture: a C++ DataFeed pipeline (pipe_command per file,
background threads, global/local shuffle) feeding trainers directly.
TPU redesign: files parse on the host into per-slot numpy batches (the
MultiSlot format: per line, for each slot, a count then that many
values), and `Executor.train_from_dataset` runs the compiled program
over those batches — the device sees the same dense feed path every
other feed uses. pipe_command still runs (subprocess per file) so
existing preprocessing commands keep working.
"""
import subprocess

import numpy as np

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetFactory:
    """reference dataset.py:DatasetFactory."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            return globals()[datafeed_class]()
        except KeyError:
            raise ValueError(
                f"datafeed class {datafeed_class} does not exist")


class DatasetBase:
    """reference dataset.py:DatasetBase — the set_* configuration
    surface plus host-side batch assembly."""

    def __init__(self):
        self.proto_desc_pipe_command = "cat"
        self.batch_size_ = 1
        self.thread_num = 1
        self.filelist = []
        self.use_var_names = []
        self.use_var_lod = []
        self.use_var_int = []
        self.hdfs_config = None
        self.download_cmd = None

    # -- configuration (reference set_* family) --
    def set_pipe_command(self, pipe_command):
        self.proto_desc_pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self.batch_size_ = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_var_names = [getattr(v, "name", str(v)) for v in var_list]
        self.use_var_lod = [bool(getattr(v, "lod_level", 0))
                            for v in var_list]
        # integer-dtype slots (embedding ids) must NOT pass through
        # float32 — ids above 2^24 would silently collide
        self.use_var_int = [
            "int" in str(getattr(v, "dtype", "float32"))
            for v in var_list]

    def set_hdfs_config(self, fs_name, fs_ugi):
        self.hdfs_config = (fs_name, fs_ugi)

    def set_download_cmd(self, download_cmd):
        self.download_cmd = download_cmd

    def desc(self):
        return (f"pipe_command: {self.proto_desc_pipe_command} "
                f"batch: {self.batch_size_} thread: {self.thread_num} "
                f"slots: {self.use_var_names}")

    # -- host-side feed assembly --
    def _slot_is_int(self):
        """One source of truth for the int-slot flags (native parse,
        python parse, and batch assembly must agree)."""
        return self.use_var_int or [False] * len(self.use_var_names)

    def _parse_line(self, line):
        """MultiSlot text format: for each slot, an integer count then
        that many values. Integer slots (per set_use_var dtype) parse
        as python ints, never floats."""
        toks = line.split()
        is_int = self._slot_is_int()
        slots, i = [], 0
        for si, _ in enumerate(self.use_var_names):
            n = int(toks[i])
            conv = int if is_int[si] else float
            vals = [conv(v) for v in toks[i + 1:i + 1 + n]]
            slots.append(vals)
            i += 1 + n
        return slots

    def _read_file_text(self, path):
        """Whole-file text after the pipe command (if any)."""
        if self.proto_desc_pipe_command not in (None, "", "cat"):
            with open(path, "rb") as fh:
                return subprocess.run(
                    self.proto_desc_pipe_command, shell=True, stdin=fh,
                    capture_output=True, check=True).stdout
        with open(path, "rb") as fh:
            return fh.read()

    def _native_parse(self, text):
        """(counts, vals) via csrc ptc_multislot_parse, or None when
        the native library is unavailable. ValueError (malformed data)
        always propagates — a re-parse must never mask it. ONE policy
        shared by the streaming and in-memory paths."""
        if not getattr(self, "use_native_parse", True):
            return None
        try:
            from ..io import native
            return native.multislot_parse(
                text, len(self.use_var_names), self._slot_is_int())
        except ValueError:
            raise
        except Exception:
            return None  # lib build/load issue: python path

    def _records(self):
        """Per file: one pipe/read, then the C MultiSlot parser (csrc
        ptc_multislot_parse — the data_feed.cc rebuild: one
        strtod/strtoll pass, records yielded as numpy views, int slots
        exact int64). A missing/unbuildable native library falls back
        to python parsing of the SAME text (the pipe command never runs
        twice); a genuinely malformed file raises ValueError from
        either path. The parse is whole-file (the reference's DataFeed
        also slurps per-file chunks); record emission streams."""
        is_int = self._slot_is_int()
        n_slots = len(self.use_var_names)
        for path in self.filelist:
            text = self._read_file_text(path)
            parsed = self._native_parse(text)
            if parsed is not None:
                counts, vals = parsed
                ivals = vals.view(np.int64)
                off = 0
                for r in range(counts.shape[0]):
                    rec = []
                    for s in range(n_slots):
                        n = int(counts[r, s])
                        rec.append(
                            (ivals if is_int[s] else vals)[off:off + n])
                        off += n
                    yield rec
            else:
                for line in text.decode().splitlines():
                    if line.strip():
                        yield self._parse_line(line)

    def _batches(self, records=None):
        """Yield dicts {var_name: np.ndarray} of batch_size records.
        Fixed-count slots stack densely; variable-count (lod) slots pad
        to the batch max (padded-dense is this framework's LoD
        redesign)."""
        buf = []
        for rec in (records if records is not None else self._records()):
            buf.append(rec)
            if len(buf) == self.batch_size_:
                yield self._assemble(buf)
                buf = []
        if buf:
            yield self._assemble(buf)

    def _assemble(self, recs):
        out = {}
        is_int = self._slot_is_int()
        for si, name in enumerate(self.use_var_names):
            col = [r[si] for r in recs]
            width = max(len(v) for v in col)
            dtype = "int64" if is_int[si] else "float32"
            arr = np.zeros((len(col), width), dtype=dtype)
            for ri, vals in enumerate(col):
                arr[ri, :len(vals)] = vals
            out[name] = arr
        return out


class QueueDataset(DatasetBase):
    """reference dataset.py:QueueDataset — streams straight from files
    (no resident copy)."""

    def __init__(self):
        super().__init__()
        self.proto_desc_name = "QueueDataset"

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset does not support local shuffle; use "
            "InMemoryDataset (reference raises the same)")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset does not support global shuffle; use "
            "InMemoryDataset (reference raises the same)")


class InMemoryDataset(DatasetBase):
    """reference dataset.py:InMemoryDataset — load_into_memory +
    local/global shuffle before training."""

    def __init__(self):
        super().__init__()
        self.proto_desc_name = "InMemoryDataset"
        self._memory = None
        self._columnar = None  # {'counts','offsets','vals','ivals'}
        self._perm = None
        self._preload = None   # (pool, futures, native_ok) in flight
        self.queue_num = None
        self.fleet_send_batch_size = None

    def set_queue_num(self, queue_num):
        self.queue_num = int(queue_num)

    def set_fleet_send_batch_size(self, n=1024):
        self.fleet_send_batch_size = int(n)

    def _probe_native(self):
        """ONE library probe decides the parse path for a whole load
        (availability is global, not per-file)."""
        native_ok = getattr(self, "use_native_parse", True)
        if native_ok:
            try:
                from ..io import native
                native.get_lib()
            except Exception:
                native_ok = False
        return native_ok

    def _load_one_file(self, path, native_ok):
        """Read (pipe runs once) + parse ONE file — the unit of work
        both the serial load and the preload thread pool schedule.
        Returns (counts, vals) on the native path, a record list on the
        python path. The pipe subprocess wait and the ctypes parse call
        both release the GIL, so these units overlap on threads."""
        text = self._read_file_text(path)
        if native_ok:
            from ..io import native
            # library is proven live: real errors (malformed data,
            # MemoryError) must raise loudly, not degrade silently
            return native.multislot_parse(
                text, len(self.use_var_names), self._slot_is_int())
        return [self._parse_line(line)
                for line in text.decode().splitlines() if line.strip()]

    def _merge_loaded(self, parsed, native_ok):
        """Merge per-file parse results (filelist order) into the
        resident store: columnar lanes on the native path, the record
        list otherwise."""
        n_slots = len(self.use_var_names)
        if native_ok:
            counts = (np.concatenate([c for c, _ in parsed])
                      if parsed else np.zeros((0, n_slots), np.int64))
            vals = (np.concatenate([v for _, v in parsed])
                    if parsed else np.zeros((0,), np.float64))
            flat = counts.reshape(-1)
            ends = np.cumsum(flat)
            self._columnar = {
                "counts": counts,
                "offsets": (ends - flat).reshape(counts.shape),
                "vals": vals,
                "ivals": vals.view(np.int64),
            }
            self._perm = np.arange(counts.shape[0])
            self._memory = None
        else:
            self._columnar = None
            self._perm = None
            recs = []
            for file_recs in parsed:
                recs.extend(file_recs)
            self._memory = recs

    def load_into_memory(self):
        """Native path keeps the parse COLUMNAR (counts/offsets/value
        lanes straight from csrc ptc_multislot_parse) so batches
        assemble by vectorized fancy-indexing and shuffling permutes an
        index array — the reference's resident-Record vector, minus the
        per-record python objects. Falls back to the python record list
        when the native library is unavailable; each file's pipe
        command runs exactly once either way. Each file's text is read,
        parsed, and dropped — peak memory is one file's bytes plus the
        accumulated parse, never all raw bytes at once."""
        native_ok = self._probe_native()
        self._merge_loaded(
            [self._load_one_file(p, native_ok) for p in self.filelist],
            native_ok)

    def preload_into_memory(self, thread_num=None):
        """Kick off load_into_memory on a thread pool (reference: the
        preload_threads of data_feed): each file's read+pipe+parse is
        one pool task, results merge in filelist order at
        wait_preload_done so record order matches the serial load
        exactly. thread_num defaults to set_thread()."""
        import concurrent.futures as cf
        nt = max(1, int(thread_num if thread_num is not None
                        else self.thread_num or 1))
        native_ok = self._probe_native()
        pool = cf.ThreadPoolExecutor(max_workers=nt)
        self._preload = (
            pool,
            [pool.submit(self._load_one_file, p, native_ok)
             for p in self.filelist],
            native_ok)

    def wait_preload_done(self):
        """Join the preload pool and publish the merged store. No-op
        when no preload is in flight (reference behaviour)."""
        preload = getattr(self, "_preload", None)
        if preload is None:
            return
        pool, futs, native_ok = preload
        self._preload = None
        try:
            results = [f.result() for f in futs]
        finally:
            pool.shutdown(wait=True)
        self._merge_loaded(results, native_ok)

    def local_shuffle(self):
        if self._memory is None and self._columnar is None:
            raise RuntimeError("call load_into_memory() first")
        from ..random import get_seed
        rng = np.random.RandomState(get_seed())
        if self._columnar is not None:
            rng.shuffle(self._perm)
        else:
            rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host: same permutation as local_shuffle (the reference
        exchanges records across trainers; with one trainer the result
        distribution is identical)."""
        self.local_shuffle()

    def release_memory(self):
        self._memory = None
        self._columnar = None
        self._perm = None

    def get_memory_data_size(self, fleet=None):
        if self._columnar is not None:
            return int(self._columnar["counts"].shape[0])
        return len(self._memory or [])

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def _batches_columnar(self):
        c = self._columnar
        counts, offsets = c["counts"], c["offsets"]
        is_int = self._slot_is_int()
        n = counts.shape[0]
        bs = self.batch_size_
        for start in range(0, n, bs):
            recs = self._perm[start:start + bs]
            out = {}
            for s, name in enumerate(self.use_var_names):
                cnt = counts[recs, s]
                w = int(cnt.max()) if len(cnt) else 0
                src = c["ivals"] if is_int[s] else c["vals"]
                ar = np.arange(w)
                idx = offsets[recs, s][:, None] + ar[None, :]
                mask = ar[None, :] < cnt[:, None]
                if len(src):
                    data = src[np.clip(idx, 0, len(src) - 1)]
                else:
                    data = np.zeros(idx.shape, src.dtype)
                out[name] = np.where(mask, data, 0).astype(
                    "int64" if is_int[s] else "float32", copy=False)
            yield out

    def _batches(self, records=None):
        if records is None and self._columnar is not None:
            return self._batches_columnar()
        if records is None and self._memory is not None:
            records = self._memory
        return super()._batches(records)
