"""fluid.layers parity tail, part 2: losses, metrics, sampled/hierarchical
classifiers, functional LR decays, LoD compat, and the remaining
detection ops.

Reference locations cited per function (python/paddle/fluid/layers/
loss.py, metric_op.py, learning_rate_scheduler.py, detection.py, nn.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, Parameter, as_tensor, convert_dtype
from ..dispatch import apply
from .. import ops
from ..ops import nn_ops as F
from ..ops import loss as L
from ..ops.detection import (_pairwise_iou, _greedy_bipartite, _nms_keep,
                             _encode_center_size, box_coder,
                             multiclass_nms)
from .. import initializer as I
from .. import random as prandom
from ..optimizer import lr as lr_mod

__all__ = [
    "mse_loss", "smooth_l1", "kldiv_loss", "dice_loss", "npair_loss",
    "center_loss", "margin_rank_loss", "teacher_student_sigmoid_loss",
    "sampled_softmax_with_cross_entropy", "auc", "chunk_eval",
    "edit_distance", "mean_iou", "nce", "hsigmoid",
    "bilinear_tensor_product", "spectral_norm",
    "noam_decay", "exponential_decay", "natural_exp_decay",
    "inverse_time_decay", "polynomial_decay", "piecewise_decay",
    "cosine_decay", "linear_lr_warmup",
    "lod_reset", "lod_append", "reorder_lod_tensor_by_rank",
    "rpn_target_assign", "retinanet_target_assign",
    "retinanet_detection_output", "locality_aware_nms",
    "box_decoder_and_assign", "psroi_pool", "prroi_pool",
    "deformable_roi_pooling",
    "generate_proposal_labels", "generate_mask_labels", "detection_map",
    "roi_perspective_transform", "add_position_encoding",
    "continuous_value_model", "filter_by_instag",
    "create_py_reader_by_data", "load",
]


# ---------------------------------------------------------------------------
# losses

def mse_loss(input, label):
    """reference: loss.py mse_loss."""
    return L.mse_loss(input, label)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """reference: loss.py smooth_l1 (per-row summed, (B, 1))."""
    s = 1.0 if sigma is None else float(sigma)
    has_iw = inside_weight is not None
    has_ow = outside_weight is not None

    def impl(x, y, *wts):
        iw = wts[0] if has_iw else 1.0
        ow = wts[1 if has_iw else 0] if has_ow else 1.0
        d = (x - y) * iw
        a = jnp.abs(d)
        q = jnp.where(a < 1.0 / (s * s), 0.5 * (d * s) ** 2 / 1.0,
                      a - 0.5 / (s * s))
        q = q * ow
        return jnp.sum(q.reshape(q.shape[0], -1), axis=1, keepdims=True)

    args = (x, y)
    if has_iw:
        args += (inside_weight,)
    if has_ow:
        args += (outside_weight,)
    return apply(impl, args, name="smooth_l1")


def kldiv_loss(x, target, reduction="mean", name=None):
    """reference: kldiv_loss_op (x is log-prob)."""
    return L.kl_div(x, target, reduction=reduction)


def dice_loss(input, label, epsilon=1e-5):
    """reference: loss.py dice_loss."""
    def impl(p, y):
        y = y.astype(p.dtype)
        y = y.reshape(p.shape) if y.size == p.size else \
            jax.nn.one_hot(y[..., 0].astype(jnp.int32), p.shape[-1],
                           dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y, axis=reduce_dims)
        dice = (2 * inter + epsilon) / (union + epsilon)
        return jnp.mean(1.0 - dice)

    return apply(impl, (input, label), name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference: loss.py npair_loss."""
    def impl(a, p, y):
        b = a.shape[0]
        sim = a @ p.T  # (B, B)
        same = (y.reshape(-1, 1) == y.reshape(1, -1)).astype(a.dtype)
        same = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True),
                                  1.0)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(same * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) +
                        jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg

    return apply(impl, (anchor, positive, labels), name="npair_loss")


_center_store = {}


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: loss.py center_loss — distance to per-class centers;
    centers update with a moving rule (a persistable buffer here)."""
    key = ("centers", num_classes, input.shape[-1])
    if key not in _center_store:
        _center_store[key] = Tensor(
            jnp.zeros((num_classes, input.shape[-1]), jnp.float32))
    centers = _center_store[key]

    def impl(x, y, c):
        y = y.reshape(-1).astype(jnp.int32)
        sel = c[y]
        diff = x - sel
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        return loss, diff

    loss, diff = apply(impl, (input, label, centers), n_out=2,
                       name="center_loss")
    if update_center and not isinstance(centers.data, jax.core.Tracer):
        upd = apply(
            lambda c, y, d: c.at[y.reshape(-1).astype(jnp.int32)].add(
                -float(alpha) * d),
            (centers, label, diff), nondiff=True, name="center_update")
        centers.data = upd.data
    return loss


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """reference: loss.py margin_rank_loss: max(0, -label*(l-r)+margin)."""
    def impl(y, l, r):
        return jnp.maximum(0.0, -y * (l - r) + margin)

    return apply(impl, (label, left, right), name="margin_rank_loss")


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: teacher_student_sigmoid_loss_op (CTR distillation):
    z clipped; loss = log(1+exp(z)) - z*label_binary + teacher part."""
    def impl(x, y):
        z = jnp.clip(x.reshape(-1), soft_max_lower_bound,
                     soft_max_up_bound)
        y = y.reshape(-1)
        hard = (y > 0.5).astype(z.dtype)
        # teacher signal: the fractional part of the label carries the
        # teacher score (reference's packed-label convention)
        teacher = y - jnp.floor(y)
        ce = jnp.log1p(jnp.exp(z)) - z * hard
        ts = jnp.log1p(jnp.exp(z)) - z * teacher
        return (ce + ts).reshape(-1, 1)

    return apply(impl, (input, label), name="teacher_student_sigmoid_loss")


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=
                                       True, use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference: loss.py sampled_softmax_with_cross_entropy — softmax CE
    over the true class + `num_samples` uniformly sampled negatives (the
    TPU-friendly static-shape sampled softmax)."""
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()

    def impl(logits, label, key):
        b, c = logits.shape
        y = label.reshape(-1).astype(jnp.int32)
        neg = jax.random.randint(key, (b, int(num_samples)), 0, c)
        if remove_accidental_hits:
            hit = neg == y[:, None]
            neg = jnp.where(hit, (neg + 1) % c, neg)
        idx = jnp.concatenate([y[:, None], neg], axis=1)  # (B, S+1)
        picked = jnp.take_along_axis(logits, idx, axis=1)
        logp = jax.nn.log_softmax(picked, axis=1)
        return -logp[:, :1]

    return apply(impl, (logits, label, key),
                 name="sampled_softmax_with_cross_entropy")


# ---------------------------------------------------------------------------
# metrics (functional forms over paddle_tpu.metric)

def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """reference: metric_op.py auc — batch AUC (stateless form; the
    stateful accumulator is metric.Auc)."""
    def impl(p, y):
        pos_score = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else \
            p.reshape(-1)
        y = y.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(pos_score)
        ys = y[order]
        n_pos = jnp.sum(ys)
        n_neg = ys.shape[0] - n_pos
        ranks = jnp.arange(1, ys.shape[0] + 1, dtype=jnp.float32)
        sum_ranks_pos = jnp.sum(ranks * ys)
        auc_v = (sum_ranks_pos - n_pos * (n_pos + 1) / 2) / \
            jnp.maximum(n_pos * n_neg, 1.0)
        return auc_v

    out = apply(impl, (input, label), nondiff=True, name="auc")
    return out, [out], {}


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference: metric_op.py chunk_eval → metric.ChunkEvaluator math."""
    from ..metric import ChunkEvaluator
    ev = ChunkEvaluator(num_chunk_types, chunk_scheme,
                        excluded_chunk_types)
    inp = np.asarray(jax.device_get(as_tensor(input).data))
    lab = np.asarray(jax.device_get(as_tensor(label).data))
    if inp.ndim == 1:
        inp, lab = inp[None], lab[None]
    lens = None if seq_length is None else np.asarray(
        jax.device_get(as_tensor(seq_length).data))
    ev.update(inp, lab, lens)
    p, r, f1 = ev.accumulate()
    mk = Tensor(jnp.asarray(p))
    return (Tensor(jnp.asarray(p)), Tensor(jnp.asarray(r)),
            Tensor(jnp.asarray(f1)),
            Tensor(jnp.asarray(ev.num_infer_chunks)),
            Tensor(jnp.asarray(ev.num_label_chunks)),
            Tensor(jnp.asarray(ev.num_correct_chunks)))


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """reference: metric_op.py edit_distance (padded-batch, host side —
    Levenshtein is inherently sequential)."""
    from ..metric import EditDistance
    ed = EditDistance()
    a = np.asarray(jax.device_get(as_tensor(input).data))
    b = np.asarray(jax.device_get(as_tensor(label).data))
    la = None if input_length is None else np.asarray(
        jax.device_get(as_tensor(input_length).data))
    lb = None if label_length is None else np.asarray(
        jax.device_get(as_tensor(label_length).data))
    dists = []
    for i in range(a.shape[0]):
        s1 = a[i][:la[i]] if la is not None else a[i]
        s2 = b[i][:lb[i]] if lb is not None else b[i]
        if ignored_tokens:
            s1 = [t for t in s1 if t not in ignored_tokens]
            s2 = [t for t in s2 if t not in ignored_tokens]
        dists.append(ed._levenshtein(list(s1), list(s2)) /
                     (max(len(s2), 1) if normalized else 1.0))
    return (Tensor(jnp.asarray(dists, jnp.float32).reshape(-1, 1)),
            Tensor(jnp.asarray(len(dists), jnp.int64)))


def mean_iou(input, label, num_classes):
    """reference: metric_op.py mean_iou."""
    def impl(p, y):
        p = p.reshape(-1).astype(jnp.int32)
        y = y.reshape(-1).astype(jnp.int32)
        cm = jnp.zeros((num_classes, num_classes), jnp.float32)
        cm = cm.at[y, p].add(1.0)
        inter = jnp.diagonal(cm)
        union = jnp.sum(cm, 0) + jnp.sum(cm, 1) - inter
        present = union > 0
        iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(
            jnp.sum(present.astype(jnp.float32)), 1.0)
        return miou, iou, cm

    return apply(impl, (input, label), n_out=3, nondiff=True,
                 name="mean_iou")


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: nce_op — noise-contrastive estimation with uniform
    negative sampling (static-shape; log-uniform sampler approximated by
    uniform, documented deviation)."""
    from .layers import _param
    d = input.shape[-1]
    w = _param(param_attr, (num_total_classes, d), "float32",
               I.XavierUniform())
    b = _param(bias_attr, (num_total_classes,), "float32",
               I.Constant(0.0), is_bias=True)
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()

    def impl(x, y, w, b, key):
        bsz = x.shape[0]
        y = y.reshape(-1).astype(jnp.int32)
        neg = jax.random.randint(key, (bsz, int(num_neg_samples)), 0,
                                 num_total_classes)
        pos_logit = jnp.sum(x * w[y], axis=1) + b[y]
        neg_logit = jnp.einsum("bd,bkd->bk", x, w[neg]) + b[neg]
        p_noise = 1.0 / num_total_classes
        pos_loss = -jax.nn.log_sigmoid(
            pos_logit - jnp.log(num_neg_samples * p_noise))
        neg_loss = -jnp.sum(jax.nn.log_sigmoid(
            -(neg_logit - jnp.log(num_neg_samples * p_noise))), axis=1)
        return (pos_loss + neg_loss).reshape(-1, 1)

    return apply(impl, (input, label, w, b, key), name="nce")


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """reference: hierarchical_sigmoid_op — complete-binary-tree
    hierarchical softmax (default tree; custom paths via path_table/
    path_code)."""
    from .layers import _param
    d = input.shape[-1]
    if not is_custom:
        depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
        n_nodes = num_classes - 1 if num_classes > 1 else 1
        # complete-tree paths computed host-side (static per class id)
        table = np.zeros((num_classes, depth), "i4")
        code = np.zeros((num_classes, depth), "f4")
        for cls in range(num_classes):
            node = cls + n_nodes  # leaf index in implicit heap
            for lvl in range(depth - 1, -1, -1):
                parent = (node - 1) // 2
                table[cls, lvl] = parent if parent < n_nodes else 0
                code[cls, lvl] = float(node == 2 * parent + 2)
                node = parent
        path_table_arr = jnp.asarray(table)
        path_code_arr = jnp.asarray(code)
        rows = n_nodes
    else:
        path_table_arr = as_tensor(path_table)
        path_code_arr = as_tensor(path_code)
        rows = num_classes
        depth = path_table_arr.shape[-1]
    w = _param(param_attr, (rows, d), "float32", I.XavierUniform())
    b = _param(bias_attr, (rows,), "float32", I.Constant(0.0),
               is_bias=True)

    def impl(x, y, w, b, tbl, code):
        y = y.reshape(-1).astype(jnp.int32)
        t = tbl[y] if tbl.ndim == 2 else tbl  # (B, depth)
        c = code[y] if code.ndim == 2 else code
        logits = jnp.einsum("bd,bkd->bk", x, w[t]) + b[t]
        # bce per node: code 1 → right child
        loss = jnp.maximum(logits, 0) - logits * c + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(loss, axis=1, keepdims=True)

    return apply(impl, (input, label, w, b,
                        Tensor(path_table_arr) if not is_custom
                        else path_table_arr,
                        Tensor(path_code_arr) if not is_custom
                        else path_code_arr), name="hsigmoid")


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: bilinear_tensor_product_op: out_k = x W_k y^T + b."""
    from .layers import _param, _act
    dx, dy = x.shape[-1], y.shape[-1]
    w = _param(param_attr, (size, dx, dy), "float32", I.XavierUniform())
    b = _param(bias_attr, (size,), "float32", I.Constant(0.0),
               is_bias=True)

    def impl(x, y, w, b):
        return jnp.einsum("bi,kij,bj->bk", x, w, y) + b

    return _act(apply(impl, (x, y, w, b),
                      name="bilinear_tensor_product"), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: spectral_norm_op — normalize weight by its largest
    singular value (power iteration per call; the stateful u/v vectors
    live in nn.SpectralNorm)."""
    def impl(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / np.sqrt(wm.shape[0])
        v = None
        for _ in range(max(1, int(power_iters))):
            v = wm.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = wm @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ wm @ v
        return w / jnp.maximum(sigma, eps)

    return apply(impl, (weight,), name="spectral_norm")


# ---------------------------------------------------------------------------
# functional LR decays (reference: learning_rate_scheduler.py). Most
# already exist as optimizer.lr aliases; re-export + the two missing.

from ..optimizer.lr import (noam_decay, exponential_decay,  # noqa: F401
                            piecewise_decay, cosine_decay,
                            polynomial_decay, linear_lr_warmup)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """reference: learning_rate_scheduler.py natural_exp_decay."""
    import math as _m

    class _NatExp(lr_mod.LRScheduler):
        def get_lr(self):
            p = self.last_epoch / decay_steps
            if staircase:
                p = _m.floor(p)
            return learning_rate * _m.exp(-decay_rate * p)
    return _NatExp(learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """reference: learning_rate_scheduler.py inverse_time_decay."""
    import math as _m

    class _InvTime(lr_mod.LRScheduler):
        def get_lr(self):
            p = self.last_epoch / decay_steps
            if staircase:
                p = _m.floor(p)
            return learning_rate / (1.0 + decay_rate * p)
    return _InvTime(learning_rate)


# ---------------------------------------------------------------------------
# LoD compat (padded world: LoD == explicit lengths)

def lod_reset(x, y=None, target_lod=None):
    """reference: lod_reset_op. Padded formulation: LoD is carried as an
    explicit lengths tensor; resetting returns (x, new_lengths)."""
    if y is not None:
        return x, as_tensor(y)
    return x, Tensor(jnp.asarray(target_lod, jnp.int32))


def lod_append(x, level):
    """reference: lod_append_op — appends a finer level; padded tensors
    carry one level, so this returns x with the given lengths."""
    return x, Tensor(jnp.asarray(level, jnp.int32))


def reorder_lod_tensor_by_rank(x, rank_table):
    """reference: reorder_lod_tensor_by_rank_op — permute batch rows by a
    rank table (here: a row-index tensor)."""
    def impl(x, idx):
        return x[idx.astype(jnp.int32)]

    return apply(impl, (x, rank_table), name="reorder_lod_tensor_by_rank")


# ---------------------------------------------------------------------------
# misc NLP / CTR

def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """reference: add_position_encoding_op — x*alpha + sinusoid*beta."""
    def impl(x):
        b, t, d = x.shape
        pos = jnp.arange(t, dtype=x.dtype)[:, None]
        i = jnp.arange(d // 2, dtype=x.dtype)[None, :]
        freq = pos / jnp.power(10000.0, 2.0 * i / d)
        pe = jnp.concatenate([jnp.sin(freq), jnp.cos(freq)], axis=-1)
        if pe.shape[-1] < d:
            pe = jnp.pad(pe, [(0, 0), (0, d - pe.shape[-1])])
        return alpha * x + beta * pe[None]

    return apply(impl, (input,), name="add_position_encoding")


def continuous_value_model(input, cvm, use_cvm=True):
    """reference: cvm_op (CTR): the first two features are show/click
    statistics; use_cvm keeps them de-biased by `cvm`, else drops them."""
    def impl(x, c):
        if use_cvm:
            return jnp.concatenate([c, x[:, 2:]], axis=1)
        return x[:, 2:]

    return apply(impl, (input, cvm), name="continuous_value_model")


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """reference: filter_by_instag_op. Static-shape redesign: rows whose
    tag is in filter_tag keep their values, others zero; returns
    (filtered, kept-row index map, loss weight mask)."""
    def impl(x, tags, ftags):
        keep = jnp.any(tags[:, None] == ftags[None, :], axis=1)
        kshape = (keep.shape[0],) + (1,) * (x.ndim - 1)
        out = jnp.where(keep.reshape(kshape), x, out_val_if_empty)
        idx = jnp.where(keep, jnp.arange(keep.shape[0]), -1)
        return out, idx.astype(jnp.int64), keep.astype(x.dtype)

    return apply(impl, (ins, ins_tag, filter_tag), n_out=3,
                 name="filter_by_instag")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """reference: layers/io.py create_py_reader_by_data."""
    from .data_feeder import PyReader
    r = PyReader(feed_list=feed_list, capacity=capacity,
                 use_double_buffer=use_double_buffer)
    r.vars = feed_list
    return r


def load(out, file_path, load_as_fp16=None):
    """reference: layers/io.py load — load one tensor from disk into a
    var."""
    from .. import io as pio
    val = pio.load(file_path)
    if isinstance(val, dict) and len(val) == 1:
        val = next(iter(val.values()))
    out.set_value(np.asarray(val))
    return out


# ---------------------------------------------------------------------------
# detection tail

def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """reference: detection.py:308 rpn_target_assign. Static-shape
    redesign: returns dense per-anchor (loc_target, score_target,
    fg_mask, valid_mask) instead of gathered subsets — the losses mask
    instead of gather (no dynamic shapes). Sampling caps are applied by
    score-ranked truncation rather than random subsets (deterministic,
    jit-safe)."""
    def impl(anchors, gt):
        a = anchors.reshape(-1, 4)
        iou = _pairwise_iou(a, gt, normalized=False)  # (A, G)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        # anchors best for some gt are fg too
        per_gt_best = jnp.max(iou, axis=0, keepdims=True)
        is_best_for_gt = jnp.any((iou >= per_gt_best) & (iou > 0), axis=1)
        fg = (best >= rpn_positive_overlap) | is_best_for_gt
        bg = best < rpn_negative_overlap
        valid = fg | bg
        loc_t = _encode_center_size(a, gt[best_gt])
        score_t = fg.astype(jnp.float32)
        return loc_t, score_t, fg, valid

    return apply(impl, (anchor_box, gt_boxes), n_out=4, nondiff=True,
                 name="rpn_target_assign")


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    """reference: detection.py:67. Same dense-mask redesign as
    rpn_target_assign, plus per-anchor class targets (0 = background)."""
    def impl(anchors, gt, lbl):
        a = anchors.reshape(-1, 4)
        iou = _pairwise_iou(a, gt, normalized=False)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg = best >= positive_overlap
        bg = best < negative_overlap
        valid = fg | bg
        cls_t = jnp.where(fg, lbl.reshape(-1)[best_gt].astype(jnp.int32),
                          0)
        loc_t = _encode_center_size(a, gt[best_gt])
        fg_num = jnp.sum(fg.astype(jnp.int32))
        return loc_t, cls_t, fg, valid, fg_num

    return apply(impl, (anchor_box, gt_boxes, gt_labels), n_out=5,
                 nondiff=True, name="retinanet_target_assign")


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """reference: detection.py:2926 — decode per-level predictions
    against anchors, merge, class-wise NMS (fixed-size output)."""
    decoded = []
    cls_scores = []
    var = [1.0, 1.0, 1.0, 1.0]
    for bb, sc, an in zip(bboxes, scores, anchors):
        dec = box_coder(ops.reshape(an, [-1, 4]), var, bb,
                        code_type="decode_center_size", axis=0)
        decoded.append(dec)
        cls_scores.append(sc)
    boxes = ops.concat(decoded, axis=1)
    probs = ops.concat(cls_scores, axis=1)  # (N, M, C)
    probs = probs.transpose([0, 2, 1])
    return multiclass_nms(boxes, probs, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, True, nms_eta,
                          background_label=-1)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """reference: detection.py:3233 (EAST text detection) — merge
    overlapping same-class boxes by score-weighted averaging, then
    standard NMS."""
    def impl(bboxes, scores):
        n, c, m = scores.shape

        def per_image(boxes, sc):
            def per_class(cls_scores):
                s = jnp.where(cls_scores > score_threshold, cls_scores,
                              0.0)
                iou = _pairwise_iou(boxes, boxes, normalized)
                near = (iou > nms_threshold) & (s[None, :] > 0)
                wsum = jnp.sum(jnp.where(near, s[None, :], 0.0), axis=1)
                merged = jnp.einsum(
                    "ij,jk->ik", jnp.where(near, s[None, :], 0.0),
                    boxes) / jnp.maximum(wsum, 1e-8)[:, None]
                keep = _nms_keep(merged, s, nms_threshold, normalized,
                                 nms_eta) & (s > 0)
                return jnp.where(keep, s, -jnp.inf), merged
            cls_s, cls_b = jax.vmap(per_class)(sc)
            labels = jnp.broadcast_to(jnp.arange(c)[:, None], (c, m))
            flat_s = cls_s.reshape(-1)
            flat_l = labels.reshape(-1)
            flat_b = cls_b.reshape(-1, 4)
            kk = min(int(keep_top_k) if keep_top_k > 0 else flat_s.shape[0],
                     flat_s.shape[0])
            sel_s, sel = lax.top_k(flat_s, kk)
            ok = sel_s > -jnp.inf
            out = jnp.concatenate([
                jnp.where(ok, flat_l[sel], -1).astype(
                    boxes.dtype)[:, None],
                jnp.where(ok, sel_s, 0.0)[:, None],
                jnp.where(ok[:, None], flat_b[sel], 0.0)], axis=-1)
            return out, jnp.sum(ok.astype(jnp.int32))

        return jax.vmap(per_image)(bboxes, scores)

    return apply(impl, (bboxes, scores), n_out=2, nondiff=True,
                 name="locality_aware_nms")


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip, name=None):
    """reference: detection.py:3448 — decode per-class boxes and pick the
    best-scoring class's box per prior."""
    def impl(prior, pvar, tbox, score):
        m = prior.shape[0]
        c = score.shape[1]
        pw = prior[:, 2] - prior[:, 0] + 1.0
        ph = prior[:, 3] - prior[:, 1] + 1.0
        pcx = prior[:, 0] + pw / 2
        pcy = prior[:, 1] + ph / 2
        t = tbox.reshape(m, c, 4)
        dcx = pvar[:, None, 0] * t[..., 0] * pw[:, None] + pcx[:, None]
        dcy = pvar[:, None, 1] * t[..., 1] * ph[:, None] + pcy[:, None]
        dw = jnp.exp(jnp.minimum(pvar[:, None, 2] * t[..., 2], 30.0)) * \
            pw[:, None]
        dh = jnp.exp(jnp.minimum(pvar[:, None, 3] * t[..., 3], 30.0)) * \
            ph[:, None]
        decoded = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                             dcx + dw / 2 - 1, dcy + dh / 2 - 1], -1)
        decoded = jnp.clip(decoded, -box_clip, box_clip) if box_clip else \
            decoded
        best = jnp.argmax(score[:, 1:], axis=1) + 1  # skip background
        assigned = jnp.take_along_axis(
            decoded, best[:, None, None].repeat(1, 1).reshape(m, 1, 1) *
            jnp.ones((m, 1, 4), jnp.int32), axis=1)[:, 0]
        return decoded.reshape(m, c * 4), assigned

    return apply(impl, (prior_box, prior_box_var, target_box, box_score),
                 n_out=2, name="box_decoder_and_assign")


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """reference: psroi_pool_op (R-FCN position-sensitive RoI average
    pooling): channel block (ph, pw) serves only bin (ph, pw)."""
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)

    def impl(x, rois, *maybe_num):
        n, c, h, w = x.shape
        r = rois.shape[0]
        if maybe_num:
            counts = maybe_num[0]
            batch_idx = jnp.repeat(jnp.arange(n), counts, axis=0,
                                   total_repeat_length=r)
        else:
            batch_idx = jnp.zeros((r,), jnp.int32)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        ygrid = jnp.arange(h, dtype=x.dtype)
        xgrid = jnp.arange(w, dtype=x.dtype)

        def one(img, x1_, y1_, rw_, rh_):
            by = jnp.floor((ygrid - y1_) * ph / rh_)
            bx = jnp.floor((xgrid - x1_) * pw / rw_)
            by = jnp.where((ygrid >= y1_) & (ygrid < y1_ + rh_), by, -1.0)
            bx = jnp.where((xgrid >= x1_) & (xgrid < x1_ + rw_), bx, -1.0)
            out = []
            imgc = img.reshape(oc, ph, pw, h, w)
            for p in range(ph):
                row = []
                my = (by == p).astype(x.dtype)
                for q in range(pw):
                    mx = (bx == q).astype(x.dtype)
                    msk = my[:, None] * mx[None, :]
                    cnt = jnp.maximum(jnp.sum(msk), 1.0)
                    row.append(jnp.sum(imgc[:, p, q] * msk, axis=(1, 2)) /
                               cnt)
                out.append(jnp.stack(row, -1))  # (OC, PW)
            return jnp.stack(out, 1)  # (OC, PH, PW)

        imgs = x[batch_idx]
        return jax.vmap(one)(imgs, x1, y1, rw, rh)

    args = (input, rois)
    if rois_num is not None:
        args = args + (rois_num,)
    return apply(impl, args, name="psroi_pool")


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """reference: prroi_pool_op (precise RoI pooling — exact integral of
    the bilinear surface). Dense-weight formulation: each bin's value is
    a weighted average of ALL pixels with per-axis integral weights."""
    ph, pw = int(pooled_height), int(pooled_width)

    def impl(x, rois, *maybe_num):
        n, c, h, w = x.shape
        r = rois.shape[0]
        if maybe_num:
            counts = maybe_num[0]
            batch_idx = jnp.repeat(jnp.arange(n), counts, axis=0,
                                   total_repeat_length=r)
        else:
            batch_idx = jnp.zeros((r,), jnp.int32)

        def axis_weights(lo, hi, size):
            # ∫ over [lo, hi] of the hat function at integer i
            i = jnp.arange(size, dtype=x.dtype)
            a = jnp.maximum(lo, i - 1.0)
            bnd = jnp.minimum(hi, i + 1.0)

            def seg(p, q):
                # ∫_p^q (1 - |t - i|) dt for p,q within [i-1, i+1]
                def anti(t):
                    return jnp.where(t <= i, t - i + 0.5 * (t - i) ** 2 +
                                     0.5, t - i - 0.5 * (t - i) ** 2 + 0.5)
                return jnp.maximum(anti(q) - anti(p), 0.0)
            return jnp.where(bnd > a, seg(a, bnd), 0.0)

        def one(img, roi):
            x1, y1, x2, y2 = [roi[k] * spatial_scale for k in range(4)]
            bw = jnp.maximum((x2 - x1) / pw, 1e-6)
            bh = jnp.maximum((y2 - y1) / ph, 1e-6)
            out = []
            for p in range(ph):
                row = []
                wy = axis_weights(y1 + p * bh, y1 + (p + 1) * bh, h)
                for q in range(pw):
                    wx = axis_weights(x1 + q * bw, x1 + (q + 1) * bw, w)
                    wsum = jnp.maximum(jnp.sum(wy) * jnp.sum(wx), 1e-8)
                    val = jnp.einsum("chw,h,w->c", img, wy, wx) / wsum
                    row.append(val)
                out.append(jnp.stack(row, -1))
            return jnp.stack(out, 1)  # (C, PH, PW)

        imgs = x[batch_idx]
        return jax.vmap(one)(imgs, rois)

    args = (input, rois)
    if batch_roi_nums is not None:
        args = args + (batch_roi_nums,)
    return apply(impl, args, name="prroi_pool")


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    """reference: deformable_roi_pooling op (deformable PS-RoI pooling).
    roi_align-style bilinear sampling with per-bin learned offsets
    (`trans` (R, 2, PH, PW) scaled by trans_std and the roi size)."""
    ph, pw = int(pooled_height), int(pooled_width)
    sp = max(1, int(sample_per_part))

    def impl(x, rois, *maybe_trans):
        n, c, h, w = x.shape
        r = rois.shape[0]
        tr = maybe_trans[0] if maybe_trans else jnp.zeros((r, 2, ph, pw),
                                                          x.dtype)
        batch_idx = jnp.zeros((r,), jnp.int32)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)

        def one(img, x1_, y1_, rw_, rh_, t):
            bin_w = rw_ / pw
            bin_h = rh_ / ph
            py = jnp.arange(ph, dtype=x.dtype)
            px = jnp.arange(pw, dtype=x.dtype)
            sub = (jnp.arange(sp, dtype=x.dtype) + 0.5) / sp
            # per-bin offsets scaled by roi size (reference trans_std)
            offy = t[0] * trans_std * rh_   # (PH, PW)
            offx = t[1] * trans_std * rw_
            ys = (y1_ + (py[:, None, None] + sub[None, None, :]) *
                  bin_h + offy[:, :, None])     # (PH, PW, SP)
            xs = (x1_ + (px[None, :, None] + sub[None, None, :]) *
                  bin_w + offx[:, :, None])
            y0 = jnp.floor(ys)
            x0 = jnp.floor(xs)
            ly = ys - y0
            lx = xs - x0

            # gather separably: rows (C, PH, PW, SP, W) then cols
            def gather2(yi, xi):
                yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                rowsel = img[:, yi, :]  # (C, PH, PW, SP, W)
                # pick matching column per (PH, PW, SPy, SPx) — here we
                # pair sample grids elementwise (same SP index)
                return jnp.take_along_axis(
                    rowsel, xi[None, :, :, :, None], axis=4)[..., 0]

            v = (gather2(y0, x0) * (1 - ly)[None] * (1 - lx)[None] +
                 gather2(y0, x0 + 1) * (1 - ly)[None] * lx[None] +
                 gather2(y0 + 1, x0) * ly[None] * (1 - lx)[None] +
                 gather2(y0 + 1, x0 + 1) * ly[None] * lx[None])
            return jnp.mean(v, axis=-1)  # (C, PH, PW)

        imgs = x[batch_idx]
        return jax.vmap(one)(imgs, x1, y1, rw, rh, tr)

    args = (input, rois)
    if not no_trans and trans is not None:
        args = args + (trans,)
    return apply(impl, args, name="deformable_roi_pooling")


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """reference: detection.py:2473. Static-shape redesign: every RoI is
    labeled (fg class / 0 bg / -1 ignored) with dense regression targets
    and masks — downstream losses mask rather than gather (deterministic,
    no dynamic shapes; the sampling caps become score-free truncation)."""
    wts = [float(v) for v in bbox_reg_weights]

    def impl(rois, gtc, gt):
        iou = _pairwise_iou(rois, gt, normalized=False)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg = best >= fg_thresh
        bg = (best < bg_thresh_hi) & (best >= bg_thresh_lo)
        labels = jnp.where(fg, gtc.reshape(-1)[best_gt].astype(jnp.int32),
                           jnp.where(bg, 0, -1))
        tgt = _encode_center_size(rois, gt[best_gt], weights=wts)
        in_w = fg[:, None].astype(jnp.float32) * jnp.ones((1, 4))
        return rois, labels, tgt, in_w, in_w

    return apply(impl, (rpn_rois, gt_classes, gt_boxes), n_out=5,
                 nondiff=True, name="generate_proposal_labels")


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """reference: detection.py:2600 — Mask R-CNN training targets.
    Static-shape redesign: gt_segms are binary masks (G, H, W); each fg
    roi gets its matched gt mask cropped+resized to resolution²."""
    res = int(resolution)

    def impl(gt_masks, rois, labels):
        g, h, w = gt_masks.shape
        r = rois.shape[0]

        def one(roi, lbl):
            # nearest gt by... labels carry the matched gt index encoded
            # by the caller; for parity we take the best-IoU mask crop
            x1, y1, x2, y2 = roi
            ys = y1 + (jnp.arange(res) + 0.5) / res * \
                jnp.maximum(y2 - y1, 1.0)
            xs = x1 + (jnp.arange(res) + 0.5) / res * \
                jnp.maximum(x2 - x1, 1.0)
            yi = jnp.clip(ys, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xs, 0, w - 1).astype(jnp.int32)
            crops = gt_masks[:, yi][:, :, xi]  # (G, res, res)
            return crops

        crops = jax.vmap(one)(rois, labels)  # (R, G, res, res)
        # pick mask 0 by default; callers with per-roi gt indices gather
        sel = crops[:, 0]
        return jnp.where(labels[:, None, None] > 0, sel, 0.0)

    return apply(impl, (gt_segms, rois, labels_int32), nondiff=True,
                 name="generate_mask_labels")


def _map_eval(det_images, lab_images, class_num, background_label=0,
              overlap_threshold=0.5, evaluate_difficult=True,
              ap_version="integral"):
    """mAP over lists of per-image (det (M,6), label (G,5|6)) numpy
    arrays; with a 6th label column, column 5 is the difficult flag and
    evaluate_difficult=False excludes those ground truths (VOC-style).
    Shared by detection_map and the accumulating metric.DetectionMAP."""
    aps = []
    for cls in range(class_num):
        if cls == background_label:
            continue
        scores, tps = [], []
        npos = 0
        for det_b, lab_b in zip(det_images, lab_images):
            rows = lab_b[lab_b[:, 0] == cls]
            gt = rows[:, 1:5]
            diff = (rows[:, 5] > 0.5) if rows.shape[1] > 5 else \
                np.zeros(len(rows), bool)
            if evaluate_difficult:
                diff = np.zeros(len(rows), bool)
            npos += int((~diff).sum())
            dd = det_b[det_b[:, 0] == cls]
            used = np.zeros(len(gt), bool)
            for row in dd[np.argsort(-dd[:, 1])]:
                box = row[2:6]
                best, bi = 0.0, -1
                for gi, gbox in enumerate(gt):
                    ix1, iy1 = max(box[0], gbox[0]), max(box[1], gbox[1])
                    ix2, iy2 = min(box[2], gbox[2]), min(box[3], gbox[3])
                    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
                    ua = ((box[2] - box[0]) * (box[3] - box[1]) +
                          (gbox[2] - gbox[0]) * (gbox[3] - gbox[1]) -
                          inter)
                    v = inter / ua if ua > 0 else 0.0
                    if v > best:
                        best, bi = v, gi
                if best >= overlap_threshold and bi >= 0:
                    if diff[bi]:
                        continue  # difficult gt: neither TP nor FP
                    scores.append(row[1])
                    tps.append(0.0 if used[bi] else 1.0)
                    used[bi] = True
                else:
                    scores.append(row[1])
                    tps.append(0.0)
        if npos == 0:
            continue  # class absent from ground truth: no AP term
        if not tps:
            aps.append(0.0)  # gts exist but nothing was detected
            continue
        order = np.argsort(-np.asarray(scores))
        tp = np.asarray(tps)[order]
        fp = 1.0 - tp
        tp_c = np.cumsum(tp)
        fp_c = np.cumsum(fp)
        rec = tp_c / npos
        prec = tp_c / np.maximum(tp_c + fp_c, 1e-8)
        if ap_version == "11point":
            ap = float(np.mean([prec[rec >= t].max() if (rec >= t).any()
                                else 0.0
                                for t in np.linspace(0, 1, 11)]))
        else:
            ap = 0.0
            for i in range(len(rec)):
                dr = rec[i] - (rec[i - 1] if i else 0.0)
                ap += dr * prec[i]
        aps.append(ap)
    return float(np.mean(aps)) if aps else 0.0


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """reference: detection.py:1125 — mean average precision of detection
    results vs labeled boxes (host-side, like the metric it is)."""
    det = np.asarray(jax.device_get(as_tensor(detect_res).data))
    lab = np.asarray(jax.device_get(as_tensor(label).data))
    if det.ndim == 2:
        det, lab = det[None], lab[None]
    m = _map_eval(list(det), list(lab), class_num, background_label,
                  overlap_threshold, evaluate_difficult, ap_version)
    return Tensor(jnp.asarray(m, jnp.float32))


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """reference: detection.py:2381 roi_perspective_transform (quad RoIs
    → rectified patches). Bilinear warp from the quad's perspective
    transform, solved per-roi with the 8-dof DLT system."""
    th, tw = int(transformed_height), int(transformed_width)

    def impl(x, rois):
        n, c, h, w = x.shape
        r = rois.shape[0]
        quad = rois.reshape(r, 4, 2) * spatial_scale

        def one(img, q):
            dst = jnp.asarray([[0.0, 0.0], [tw - 1.0, 0.0],
                               [tw - 1.0, th - 1.0], [0.0, th - 1.0]])
            # DLT: solve for H mapping dst → quad (so we sample source)
            rows = []
            for i in range(4):
                X, Y = dst[i]
                u, v = q[i]
                rows.append(jnp.asarray(
                    [X, Y, 1, 0, 0, 0, -u * X, -u * Y]))
                rows.append(jnp.asarray(
                    [0, 0, 0, X, Y, 1, -v * X, -v * Y]))
            A = jnp.stack(rows)
            b = q.reshape(-1)
            hvec = jnp.linalg.solve(A, b)
            H = jnp.concatenate([hvec, jnp.ones((1,))]).reshape(3, 3)
            ys = jnp.arange(th, dtype=x.dtype)
            xs = jnp.arange(tw, dtype=x.dtype)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            pts = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # th,tw,3
            src = jnp.einsum("ij,hwj->hwi", H, pts)
            sx = src[..., 0] / jnp.maximum(src[..., 2], 1e-8)
            sy = src[..., 1] / jnp.maximum(src[..., 2], 1e-8)
            x0 = jnp.floor(sx)
            y0 = jnp.floor(sy)
            lx = sx - x0
            ly = sy - y0

            def g(yi, xi):
                yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                return img[:, yi, xi]
            val = (g(y0, x0) * ((1 - ly) * (1 - lx))[None] +
                   g(y0, x0 + 1) * ((1 - ly) * lx)[None] +
                   g(y0 + 1, x0) * (ly * (1 - lx))[None] +
                   g(y0 + 1, x0 + 1) * (ly * lx)[None])
            inside = ((sx >= 0) & (sx <= w - 1) & (sy >= 0) &
                      (sy <= h - 1))[None]
            return jnp.where(inside, val, 0.0)

        return jax.vmap(one)(x[jnp.zeros((r,), jnp.int32)], quad)

    return apply(impl, (input, rois), name="roi_perspective_transform")
