"""contrib stat utilities: model_stat, memory_usage_calc, op_frequence
(reference: python/paddle/fluid/contrib/{model_stat.py:40 summary,
memory_usage_calc.py:46 memory_usage, op_frequence.py}).

The reference walks static Program op-descs. The rebuild offers both
entry points that matter here: Layer-based (params/FLOPs from a shaped
forward with capture hooks — the dygraph-natural form) and function-based
(op frequency from the actual traced jaxpr, which is what XLA compiles)."""
from __future__ import annotations

from collections import Counter, OrderedDict

import numpy as np

__all__ = ["summary", "memory_usage", "op_freq_statistic"]

_DTYPE_BYTES = {"float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
                "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1,
                "bool": 1}


def _flops_of(layer, x_shape, y_shape):
    """Per-layer MAC-based FLOPs (reference model_stat counts convs and
    muls the same way)."""
    from .. import nn
    if isinstance(layer, nn.Linear):
        out = int(np.prod(y_shape))
        return out * layer.weight.shape[0] * 2
    if isinstance(layer, nn.Conv2D):
        kh, kw = layer.weight.shape[-2:]
        cin = layer.weight.shape[1]
        out = int(np.prod(y_shape))
        return out * cin * kh * kw * 2
    return 0


def summary(model, input_spec=None, input=None):
    """Layer/param/FLOPs table (reference: model_stat.py:40 summary).

    model: an nn.Layer; input_spec: example input(s) (Tensor/ndarray or
    tuple) run through the model with shape-capture hooks. Returns the
    table text and prints it."""
    from ..tensor import Tensor
    from .. import to_tensor

    rows = []
    handles = []

    def cap(name):
        def hook(layer, inputs, output):
            x = inputs[0] if inputs else None
            xs = tuple(getattr(x, "shape", ())) if x is not None else ()
            ys = tuple(getattr(output, "shape", ())) \
                if not isinstance(output, (tuple, list)) else \
                tuple(getattr(output[0], "shape", ()))
            n_params = sum(int(np.prod(p.shape))
                           for p in layer._parameters.values()
                           if p is not None)
            rows.append((name or type(layer).__name__,
                         type(layer).__name__, xs, ys, n_params,
                         _flops_of(layer, xs, ys)))
            return None
        return hook

    for name, sub in model.named_sublayers():
        if not sub._sub_layers:  # leaves only
            handles.append(sub.register_forward_post_hook(cap(name)))
    example = input if input is not None else input_spec
    if example is not None:
        model.eval()
        if not isinstance(example, (tuple, list)):
            example = (example,)
        example = tuple(to_tensor(np.asarray(e)) if not isinstance(e, Tensor)
                        else e for e in example)
        from .. import autograd
        with autograd.no_grad():
            model(*example)
    for h in handles:
        h.remove()

    total_params = sum(r[4] for r in rows)
    total_flops = sum(r[5] for r in rows)
    lines = [f"{'layer':<28}{'type':<14}{'input':<18}{'output':<18}"
             f"{'params':>10}{'FLOPs':>14}"]
    for r in rows:
        lines.append(f"{r[0]:<28}{r[1]:<14}{str(r[2]):<18}{str(r[3]):<18}"
                     f"{r[4]:>10}{r[5]:>14}")
    lines.append(f"Total params: {total_params:,}  "
                 f"({total_params * 4 / 1024 / 1024:.2f} MB fp32)")
    lines.append(f"Total FLOPs: {total_flops:,} "
                 f"({total_flops / 1e9:.3f} GFLOPs/sample-batch)")
    text = "\n".join(lines)
    print(text)
    return OrderedDict(total_params=total_params, total_flops=total_flops,
                       table=text)


def memory_usage(program_or_model, batch_size=1):
    """Rough training-memory estimate in MB (reference:
    memory_usage_calc.py:46 — sums var bytes with a lower/upper band).

    Accepts a static Program (sums its recorded vars) or an nn.Layer
    (params + grads + adam-style slots as the steady-state band)."""
    from ..nn.layer import Layer

    if isinstance(program_or_model, Layer):
        p_bytes = 0
        for p in program_or_model.parameters():
            nbytes = int(np.prod(p.shape)) * _DTYPE_BYTES.get(
                str(p.data.dtype), 4)
            p_bytes += nbytes
        low = p_bytes * 2 / 1024 / 1024          # params + grads
        high = p_bytes * 4 / 1024 / 1024         # + two adam slots
        return low, high

    program = program_or_model
    total = 0
    for name, v in program.global_block().vars.items():
        shape = [batch_size if (d is None or d < 0) else d
                 for d in (v.shape or ())]
        total += int(np.prod(shape)) * _DTYPE_BYTES.get(
            str(getattr(v, "dtype", "float32")), 4)
    mb = total / 1024 / 1024
    # the reference reports a +-30% band around the op-desc estimate
    return mb * 0.7, mb * 1.3


def op_freq_statistic(program_or_fn, *example_args):
    """Op frequency count (reference: op_frequence.py op_freq_statistic).

    For a static Program: counts recorded OpNode types. For a callable +
    example args: counts primitive names in the TRACED jaxpr — the op
    stream XLA actually compiles."""
    if callable(program_or_fn) and not hasattr(program_or_fn,
                                               "global_block"):
        import jax
        jaxpr = jax.make_jaxpr(program_or_fn)(*example_args)

        def walk(jx, c):
            for eqn in jx.eqns:
                c[eqn.primitive.name] += 1
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr, c)
            return c

        return Counter(walk(jaxpr.jaxpr, Counter()))
    program = program_or_fn
    return Counter(op.type or "unknown"
                   for op in program.global_block().ops)
