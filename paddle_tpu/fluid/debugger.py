"""fluid.debugger — Program visualization + pretty printing (reference:
python/paddle/fluid/debugger.py:1 draw_block_graphviz /
pprint_program_codes, graphviz.py Graph builder).

Works on this framework's static Program (static/__init__.py Block of
OpNodes): ops and vars become graphviz nodes with data edges. The DOT
text is self-contained — no graphviz python binding needed; `dot -Tpng`
renders it."""
from __future__ import annotations

__all__ = ["draw_block_graphviz", "pprint_block_codes",
           "pprint_program_codes", "program_to_dot"]


def _esc(s):
    return str(s).replace('"', r'\"')


def program_to_dot(program, graph_name="program"):
    """DOT source for a static Program's global block (ops = boxes,
    vars = ellipses, data deps = edges)."""
    block = program.global_block()
    lines = [f'digraph "{_esc(graph_name)}" {{',
             "  rankdir=TB;",
             '  node [fontsize=10];']
    feed_names = set(program.feed_vars)
    param_names = set(program.param_vars)
    seen_vars = set()

    def var_node(name):
        if name in seen_vars:
            return
        seen_vars.add(name)
        v = block.vars.get(name)
        shape = getattr(v, "shape", None)
        label = f"{name}\\n{shape}" if shape is not None else name
        if name in feed_names:
            color = "lightblue"
        elif name in param_names:
            color = "lightyellow"
        else:
            color = "white"
        lines.append(f'  "v_{_esc(name)}" [label="{_esc(label)}", '
                     f'shape=ellipse, style=filled, fillcolor={color}];')

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        typ = op.type or "op"
        lines.append(f'  "{op_id}" [label="{_esc(typ)}", shape=box, '
                     'style=filled, fillcolor=lightgrey];')
        for name in op.inputs:
            var_node(name)
            lines.append(f'  "v_{_esc(name)}" -> "{op_id}";')
        for name in op.outputs:
            var_node(name)
            lines.append(f'  "{op_id}" -> "v_{_esc(name)}";')
    lines.append("}")
    return "\n".join(lines)


def draw_block_graphviz(block_or_program, highlights=None, path=None):
    """reference: debugger.py draw_block_graphviz — write the block's
    graph as DOT to `path` (default ./program.dot); returns the DOT
    text."""
    program = getattr(block_or_program, "program", block_or_program)
    dot = program_to_dot(program)
    path = path or "./program.dot"
    with open(path, "w") as f:
        f.write(dot)
    return dot


def pprint_block_codes(block, show_backward=False):
    """Program-as-pseudocode text (reference: debugger.py
    pprint_block_codes)."""
    out = []
    for i, op in enumerate(block.ops):
        ins = ", ".join(op.inputs)
        outs = ", ".join(op.outputs)
        attrs = ""
        if op.attrs:
            attrs = " {" + ", ".join(
                f"{k}={v!r}" for k, v in sorted(op.attrs.items())
                if not callable(v)) + "}"
        out.append(f"{i:4d}: {outs or '_'} = {op.type or 'op'}({ins})"
                   f"{attrs}")
    return "\n".join(out)


def pprint_program_codes(program):
    return pprint_block_codes(program.global_block())
