"""paddle_tpu.tensor — the core Tensor/Parameter types.

TPU-native rebuild of the reference's Variable/LoDTensor/Parameter stack
(reference: python/paddle/fluid/framework.py Variable/Parameter;
paddle/fluid/framework/lod_tensor.h). Instead of a C++ LoDTensor with
device-specific allocations, a Tensor here wraps a `jax.Array` (device
placement and memory are owned by XLA's arena) plus the dygraph autograd
metadata (stop_gradient, accumulated grad, tape linkage).

Tensors are pytree-registered so whole models/optimizer states can flow
through `jax.jit` / `pjit` as pytrees.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# dtype utilities

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "int32": jnp.int32, "int64": jnp.int64,
    "int16": jnp.int16, "int8": jnp.int8, "uint8": jnp.uint8,
    "bool": jnp.bool_, "complex64": jnp.complex64,
}

_default_dtype = jnp.float32

# optimizer.arena coherence hook: set (module-wide) while any flat param
# arena is alive; called as _arena_hook(tensor, "read"|"write") so stale
# per-leaf views materialize lazily and external writes trigger a repack.
_arena_hook = None


def set_default_dtype(dtype):
    """Set the default floating dtype used for tensor creation (cf. reference
    fluid default FP32)."""
    global _default_dtype
    _default_dtype = convert_dtype(dtype)


def get_default_dtype():
    return _default_dtype


def convert_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES[dtype]
    elif not isinstance(dtype, type):
        dtype = jnp.dtype(dtype).type
    # canonicalize 64-bit requests when x64 is off (TPU default) — avoids
    # per-op truncation warnings; paddle's int64 labels become int32 lanes
    if not jax.config.jax_enable_x64:
        dtype = {jnp.int64: jnp.int32, jnp.float64: jnp.float32,
                 np.int64: jnp.int32, np.float64: jnp.float32}.get(dtype,
                                                                   dtype)
    return dtype


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


# ---------------------------------------------------------------------------
# Tensor

class Tensor:
    """Eager tensor wrapping a jax.Array.

    Mirrors the dygraph VarBase of the reference (paddle/fluid/imperative/
    layer.h + python/paddle/fluid/framework.py Variable): holds data, a
    ``stop_gradient`` flag, and an accumulated ``grad``. The tape node is
    attached by the op dispatcher (see paddle_tpu/dispatch.py).
    """

    __slots__ = ("data", "stop_gradient", "_grad", "_tape_node", "name",
                 "persistable", "_graph_freed", "error_clip", "grad_clip",
                 "__weakref__")

    def __init__(self, data, stop_gradient=True, name=None, dtype=None):
        if isinstance(data, Tensor):
            data = data.data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            arr = np.asarray(data)
            if dtype is None and arr.dtype == np.float64:
                dtype = _default_dtype
            if dtype is None and arr.dtype == np.int64 and arr.ndim == 0:
                dtype = jnp.int64
            data = jnp.asarray(arr, dtype=convert_dtype(dtype))
        elif dtype is not None:
            data = data.astype(convert_dtype(dtype))
        self.data = data
        self.stop_gradient = bool(stop_gradient)
        self._grad = None
        self._tape_node = None
        self._graph_freed = False
        self.name = name
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self.data.shape)

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def size(self):
        return int(np.prod(self.data.shape)) if self.data.shape else 1

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value

    def numpy(self):
        if _arena_hook is not None:
            _arena_hook(self, "read")
        return np.asarray(jax.device_get(self.data))

    def item(self):
        return self.numpy().item()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.data.shape[0]

    def __repr__(self):
        return (f"Tensor(shape={self.shape}, dtype={self.data.dtype}, "
                f"stop_gradient={self.stop_gradient},\n{self.data})")

    def __bool__(self):
        return bool(self.data)

    def __int__(self):
        return int(self.data)

    def __float__(self):
        return float(self.data)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd
        autograd.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_gradient(self):
        self._grad = None

    def clear_grad(self):
        self._grad = None

    def detach(self):
        t = Tensor(self.data, stop_gradient=True, name=self.name)
        return t

    def stop_grad_(self):
        self.stop_gradient = True
        return self

    # -- in-place-ish helpers (dygraph parity) ------------------------------
    def set_value(self, value):
        """Overwrite the payload in place (reference: Variable.set_value).
        Copies device arrays so the holder never aliases a buffer that a
        donated compiled step may later invalidate."""
        if _arena_hook is not None:
            _arena_hook(self, "write")
        if isinstance(value, Tensor):
            value = value.data
        was_jax = isinstance(value, jax.Array)
        value = jnp.asarray(value, dtype=self.data.dtype)
        if tuple(value.shape) != tuple(self.data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self.data.shape}")
        if was_jax and not isinstance(value, jax.core.Tracer):
            value = jnp.array(value, copy=True)
        # keep the holder's mesh placement: restoring a checkpoint into a
        # dp×tp-sharded parameter must not silently re-replicate it
        old = self.data
        if (isinstance(old, jax.Array)
                and not isinstance(old, jax.core.Tracer)
                and not isinstance(value, jax.core.Tracer)):
            try:
                if value.sharding != old.sharding:
                    value = jax.device_put(value, old.sharding)
            except (AttributeError, ValueError):
                pass
        self.data = value
        return self

    def copy_(self, other):
        return self.set_value(other)

    def astype(self, dtype):
        from . import ops
        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    # pickling (checkpoints / save_inference_model)
    def __getstate__(self):
        return {"data": self.numpy(), "stop_gradient": self.stop_gradient,
                "name": self.name, "persistable": self.persistable}

    def __setstate__(self, state):
        self.data = jnp.asarray(state["data"])
        self.stop_gradient = state["stop_gradient"]
        self.name = state["name"]
        self.persistable = state["persistable"]
        self._grad = None
        self._tape_node = None
        self._graph_freed = False

    # numeric magic methods are attached by paddle_tpu.ops at import time to
    # avoid a circular import (ops needs Tensor for dispatch).


class Parameter(Tensor):
    """Trainable tensor (reference: framework.py Parameter). Defaults to
    requiring grad and being persistable."""

    __slots__ = ("trainable", "regularizer", "optimize_attr")

    def __init__(self, data, name=None, trainable=True, dtype=None):
        super().__init__(data, stop_gradient=not trainable, name=name,
                         dtype=dtype)
        self.trainable = trainable
        self.persistable = True
        self.regularizer = None
        self.optimize_attr = {"learning_rate": 1.0}

    def __repr__(self):
        return (f"Parameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.data.dtype}, trainable={self.trainable})")

    def __getstate__(self):
        state = super().__getstate__()
        state["trainable"] = self.trainable
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self.trainable = state.get("trainable", True)
        self.stop_gradient = not self.trainable
        self.regularizer = None
        self.optimize_attr = {"learning_rate": 1.0}


# ---------------------------------------------------------------------------
# pytree registration: Tensor flattens to its payload so models / states can
# cross jit/pjit boundaries as pytrees.

def _tensor_flatten(t):
    return (t.data,), (type(t), t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    cls, stop_gradient, name = aux
    t = Tensor.__new__(cls)
    Tensor.__init__(t, children[0], stop_gradient=stop_gradient, name=name)
    if cls is Parameter:
        t.trainable = not stop_gradient
        t.persistable = True
        t.regularizer = None
        t.optimize_attr = {"learning_rate": 1.0}
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(Parameter, _tensor_flatten,
                                   _tensor_unflatten)


# ---------------------------------------------------------------------------
# creation API

def to_tensor(data, dtype=None, stop_gradient=True, name=None):
    """paddle.to_tensor equivalent."""
    return Tensor(data, stop_gradient=stop_gradient, name=name, dtype=dtype)


def as_tensor(x):
    """Coerce python scalars / numpy arrays to Tensor for op dispatch."""
    if isinstance(x, Tensor):
        return x
    return Tensor(x)


def unbind(input, axis=0):
    """reference tensor/manipulation.py:unbind — module-level twin of
    Tensor.unbind (lazy import: ops depends on this module)."""
    from .ops.manip import unbind as _unbind
    return _unbind(input, axis)
