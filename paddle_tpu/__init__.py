"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capabilities of the reference PaddlePaddle
(v1.7 Fluid era, /root/reference) designed for TPU hardware: jax/XLA for
the compute path (MXU-friendly ops, one compiled computation per train
step), `jax.sharding.Mesh` + shard_map for distribution (ICI collectives
instead of NCCL), Pallas for fused kernels, and a C++ host runtime for the
input pipeline.

Top-level API mirrors the reference's `paddle` / `paddle.fluid` surface:
Tensor, nn.Layer, optimizers, static Program/Executor, fleet, io.
"""
__version__ = "0.1.0"

from .tensor import (Tensor, Parameter, to_tensor, set_default_dtype,
                     get_default_dtype)
from .random import seed, get_seed
from . import autograd
from .autograd import no_grad, enable_grad, grad
from . import ops
from .ops import *  # noqa: F401,F403  (functional surface: paddle.add etc.)
from . import nn
from . import optimizer
from .optimizer import lr  # noqa: F401
from . import initializer
from . import regularizer
from . import clip
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm
# the clip *module* import above shadowed the clip op — rebind the function
# (the module stays importable as `paddle_tpu.clip` via sys.modules)
from .ops.math import clip  # noqa: F811
from .param_attr import ParamAttr, WeightNormParamAttr
from . import device
from .device import (CPUPlace, TPUPlace, CUDAPlace, set_device, get_device,
                     is_compiled_with_cuda, device_count,
                     enable_compilation_cache)

# framework-level namespaces filled in by submodules as they land
from . import jit
from . import static
from . import io
from . import metric
from . import amp
from . import parallel
from . import distributed
from . import models
from . import utils
from . import inference
from . import fluid
from . import reader
from .reader import batch
from . import compat
from . import sysconfig
from . import distribution
from . import quantization
from . import slim
from . import fleet
from . import dataset
from . import monitor
from . import resilience
from . import serving

# PADDLE_TPU_MONITOR=1 turns the metrics runtime on for the whole
# process (sink location via PADDLE_TPU_MONITOR_DIR); default stays
# off — a single flag check on the dispatch fast path.
import os as _os
if _os.environ.get("PADDLE_TPU_MONITOR", "") not in ("", "0", "false",
                                                     "False"):
    monitor.enable()

# dygraph/static mode management (reference: fluid.enable_dygraph /
# paddle.enable_static). Dygraph is the default here (modern surface).
from .dispatch import in_static_mode as in_static_mode  # noqa


def enable_static():
    from . import static as _static
    _static.enable_static()


def disable_static():
    from . import static as _static
    _static.disable_static()


def in_dynamic_mode():
    return not in_static_mode()


# reference python/paddle/__init__.py top-level name parity tail
def _reduce_alias(fn):
    # reference reduce_* signature uses dim/keep_dim keywords
    def f(input, dim=None, keep_dim=False, name=None):
        return fn(input, axis=dim, keepdim=keep_dim)
    f.__name__ = "reduce_" + fn.__name__
    return f


reduce_sum = _reduce_alias(ops.sum)
reduce_mean = _reduce_alias(ops.mean)
reduce_max = _reduce_alias(ops.max)
reduce_min = _reduce_alias(ops.min)
reduce_prod = _reduce_alias(ops.prod)
reduce_all = _reduce_alias(ops.all)
reduce_any = _reduce_alias(ops.any)
manual_seed = seed
shuffle = reader.shuffle


def in_dygraph_mode():
    """reference fluid framework.py:in_dygraph_mode."""
    return not in_static_mode()


def enable_dygraph(place=None):
    if in_static_mode():
        disable_static()


def disable_dygraph():
    if not in_static_mode():
        enable_static()


def save(obj, path, protocol=4):
    """paddle.save → io.save."""
    from . import io as _io
    return _io.save(obj, path, protocol=protocol)


def load(path, **kw):
    """paddle.load → io.load. Unsupported options raise rather than
    silently changing semantics."""
    if kw:
        raise ValueError(f"paddle_tpu.load: unsupported options {set(kw)}")
    from . import io as _io
    return _io.load(path)


from . import hapi  # noqa: E402  (high-level Model API)
from . import incubate  # noqa: E402


from . import framework  # noqa: E402
from . import imperative  # noqa: E402
