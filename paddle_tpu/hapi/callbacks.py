"""hapi callbacks (reference: incubate/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint; EarlyStopping is the 2.x-era addition the
API grew into)."""
from __future__ import annotations

import time

import numpy as np


class Callback:
    """reference hapi/callbacks.py:Callback — hook points around fit."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def call(self, name, *args, **kw):
        for c in self.callbacks:
            getattr(c, name)(*args, **kw)


class ProgBarLogger(Callback):
    """reference hapi/callbacks.py:ProgBarLogger — per-epoch line logger
    (plain-line redesign of the carriage-return progressbar: friendlier
    to captured logs)."""

    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        self._seen = 0

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self._seen += logs.get("batch_size", 1)
        if self.verbose and self.log_freq and \
                (step + 1) % self.log_freq == 0:
            items = ", ".join(f"{k}={self._fmt(v)}"
                              for k, v in logs.items()
                              if k != "batch_size")
            print(f"epoch {self._epoch} step {step + 1}: {items}",
                  flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if not self.verbose:
            return
        dt = time.time() - self._t0
        items = ", ".join(f"{k}={self._fmt(v)}"
                          for k, v in (logs or {}).items()
                          if k != "batch_size")
        print(f"epoch {epoch} done in {dt:.1f}s: {items}", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}={self._fmt(v)}"
                              for k, v in (logs or {}).items()
                              if k != "batch_size")
            print(f"eval: {items}", flush=True)

    @staticmethod
    def _fmt(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return "[" + ", ".join(f"{float(x):.4f}" for x in
                                   np.ravel(v)) + "]"
        try:
            return f"{float(v):.4f}"
        except (TypeError, ValueError):
            return str(v)


class ModelCheckpoint(Callback):
    """reference hapi/callbacks.py:ModelCheckpoint — save every
    save_freq epochs into save_dir/{epoch} and save_dir/final."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    """Stop fit() when a monitored metric stops improving."""

    def __init__(self, monitor="loss", patience=0, min_delta=0.0,
                 mode="min"):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped = False

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        better = (self.best is None or
                  (cur < self.best - self.min_delta
                   if self.mode == "min"
                   else cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                self.model.stop_training = True
