"""hapi.datasets (reference: incubate/hapi/datasets/{mnist,flowers,
folder}.py — map-style Datasets with transform hooks, usable with
io.DataLoader).

MNIST/Flowers wrap the fluid-era paddle_tpu.dataset sources (which fall
back to deterministic synthetic data in this zero-egress environment);
DatasetFolder/ImageFolder walk a class-per-directory tree on local disk
(reference folder.py:60) loading through PIL when present."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "Flowers", "DatasetFolder", "ImageFolder"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".webp",
                  ".npy")


class MNIST(Dataset):
    """reference: datasets/mnist.py — mode 'train'|'test', optional
    transform(img) -> img. Images are 28x28 float32 ALREADY normalized
    to [-1, 1] (the reference mnist reader's (px/127.5)-1 semantics) —
    do not renormalize by 255."""

    def __init__(self, mode="train", transform=None, return_label=True):
        from ..dataset import mnist as _mnist
        images, labels = _mnist.train_arrays() if mode == "train" \
            else _mnist.test_arrays()
        self.images = np.asarray(images, "float32")
        self.labels = np.asarray(labels, "int64")
        self.mode = mode
        self.transform = transform
        self.return_label = return_label

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].reshape(28, 28)
        if self.transform is not None:
            img = self.transform(img)
        if self.return_label:
            return img, np.int64(self.labels[idx])
        return (img,)


class Flowers(Dataset):
    """reference: datasets/flowers.py."""

    def __init__(self, mode="train", transform=None):
        from ..dataset import flowers as _flowers
        reader = {"train": _flowers.train, "test": _flowers.test,
                  "valid": _flowers.valid}[mode]()
        samples = list(reader())
        self.images = np.stack([np.asarray(s[0], "float32")
                                for s in samples])
        self.labels = np.asarray([s[1] for s in samples], "int64")
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


def has_valid_extension(filename, extensions=IMG_EXTENSIONS):
    """reference: folder.py:24."""
    return filename.lower().endswith(tuple(extensions))


def _default_loader(path):
    if path.lower().endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))
    except ImportError:  # pragma: no cover
        from ..dataset import image as _img
        return _img.load_image(path)


def make_dataset(directory, class_to_idx, extensions=IMG_EXTENSIONS,
                 is_valid_file=None):
    """reference: folder.py:37 — (path, class_idx) list over a
    class-per-subdir tree."""
    samples = []
    check = is_valid_file or (
        lambda p: has_valid_extension(p, extensions))
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, files in sorted(os.walk(d)):
            for f in sorted(files):
                path = os.path.join(root, f)
                if check(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """reference: folder.py:60 — root/class_x/xxx.png layout."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx, extensions,
                                    is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root} "
                               f"(extensions {extensions})")
        self.loader = loader or _default_loader
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)


class ImageFolder(Dataset):
    """reference: folder.py:197 — flat (unlabeled) image list."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        check = is_valid_file or (
            lambda p: has_valid_extension(p, extensions))
        samples = []
        for r, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                p = os.path.join(r, f)
                if check(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(f"no valid files found under {root}")
        self.samples = samples
        self.loader = loader or _default_loader
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)
