"""hapi Model (reference: incubate/hapi/model.py:652 Model —
prepare/fit/evaluate/predict/train_batch/eval_batch/save/load).

TPU redesign: the reference keeps separate dygraph/static adapter classes
(DynamicGraphAdapter / StaticGraphAdapter, model.py:137/586); here there
is ONE path — the train and eval steps are ordinary dygraph functions that
jit.to_static compiles into single donated XLA executables, so `fit` runs
one fused computation per batch on the MXU.
"""
from __future__ import annotations

import os

import numpy as np

from .. import io as pio
from .. import jit
from .. import monitor as _monitor
from ..nn import Layer
from .callbacks import CallbackList, ProgBarLogger, ModelCheckpoint
from .metrics import Metric


class Input:
    """reference hapi/model.py:Input — an input spec (shape/dtype/name)."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape or ())
        self.dtype = dtype
        self.name = name


def set_device(device):
    """reference hapi/model.py:set_device."""
    from ..device import set_device as _sd
    return _sd(device)


class Model(Layer):
    """High-level trainable container. Use either style:

    - wrap: ``Model(network)`` with any nn.Layer
    - subclass: ``class MyModel(hapi.Model)`` defining forward()
    """

    def __init__(self, network=None, inputs=None, labels=None):
        super().__init__()
        if network is not None:
            self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_fn = None
        self._pred_fn = None
        self._bucket_buckets = None  # fit(bucket=True) sets [batch_size]
        self._guard_traced = False   # nan_guard baked into _train_step?
        self._mesh_plan = None       # fit(mesh_plan=) resolved MeshPlan
        self._memory = None          # fit(memory=) MemoryPolicy | "auto"
        self._train_step_split = False  # offload: fwd/bwd + eager apply
        self._split_trainables = None
        self._split_has_grad = None
        self.stop_training = False

    # -- wiring ------------------------------------------------------------

    def forward(self, *args):
        if hasattr(self, "network"):
            return self.network(*args)
        raise NotImplementedError(
            "subclass hapi.Model and define forward(), or pass a network")

    def prepare(self, optimizer=None, loss_function=None, metrics=None,
                inputs=None, labels=None, device=None):
        """reference hapi/model.py:1030 prepare."""
        self._optimizer = optimizer
        self._loss = loss_function
        ms = metrics or []
        ms = ms if isinstance(ms, (list, tuple)) else [ms]
        for m in ms:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be hapi.Metric, got {m}")
        self._metrics = list(ms)
        self._train_step = None  # recompile on next batch
        self._eval_fn = None

    # -- single-batch ops --------------------------------------------------

    def _compute_loss(self, outputs, labels):
        losses = self._loss(outputs, labels)
        total = losses[0]
        for lo in losses[1:]:
            total = total + lo
        return total

    def train_batch(self, inputs, labels=None):
        """reference hapi/model.py:train_batch — one optimizer step;
        compiled on first call."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else \
            ([] if labels is None else [labels])
        if self._train_step is None:
            self._compile_train_step(inputs)
        from ..tensor import to_tensor
        args = [to_tensor(a) for a in list(inputs) + list(labels)]
        if self._train_step_split:
            # offload split step: the jitted part is fwd/bwd only, with
            # the grads threaded out as explicit outputs; the fused
            # apply runs eagerly so the arena moments can live on host
            # between applies (the fwd/bwd executable never carries
            # them). The grads round-trip through p._grad exactly as
            # the fused path would have seen them.
            outs = self._train_step(*args)
            loss = outs[0]
            gi = iter(outs[1:])
            for p, has in zip(self._split_trainables,
                              self._split_has_grad):
                p._grad = next(gi).data if has else None
            self._optimizer.step()
            self._optimizer.clear_grad()
        else:
            loss = self._train_step(*args)
        return [float(np.asarray(loss.numpy()))]

    def _compile_train_step(self, inputs):
        """Build the compiled train step under the active memory
        policy: remat joins the to_static cache key, master_weights
        wraps the body in amp.auto_cast over the arena's fp32 master,
        and offload switches to the split fwd/bwd + eager-apply shape."""
        from ..memory_plan import MemoryPolicy
        pol = self._memory if isinstance(self._memory, MemoryPolicy) \
            else None
        remat = pol.remat if pol is not None else None
        mw = pol is not None and pol.master_weights
        offload = pol is not None and pol.offload
        if mw:
            import jax.numpy as jnp
            self._optimizer.set_flat_arena(True)
            self._optimizer._arena_view_dtype = jnp.bfloat16

        def fwd_loss(ins, labs):
            if mw:
                from .. import amp as _amp
                with _amp.auto_cast(True, dtype="bfloat16"):
                    outs = self(*ins)
                    return self._compute_loss(outs, list(labs))
            outs = self(*ins)
            return self._compute_loss(outs, list(labs))

        if offload:
            from ..memory_plan import attach_offload
            from ..tensor import Tensor
            attach_offload(self._optimizer)
            trainables = [p for p in self.parameters()
                          if not p.stop_gradient]
            self._split_trainables = trainables
            self._split_has_grad = has = []

            def fwd_bwd(*args):
                n_in = len(inputs)
                loss = fwd_loss(args[:n_in], args[n_in:])
                loss.backward()
                # which params actually received grads is a structural
                # fact of the trace — record it so the eager apply
                # skips exactly the params the fused path would skip
                has.clear()
                grads = []
                for p in trainables:
                    has.append(p._grad is not None)
                    if p._grad is not None:
                        grads.append(Tensor(p._grad))
                return tuple([loss] + grads)

            self._train_step = jit.to_static(
                fwd_bwd, models=[self], optimizers=[],
                bucket=self._bucket_buckets is not None,
                buckets=self._bucket_buckets, plan=self._mesh_plan,
                remat=remat)
            self._train_step_split = True
            return

        def step(*args):
            n_in = len(inputs)
            loss = fwd_loss(args[:n_in], args[n_in:])
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
            return loss
        self._train_step = jit.to_static(
            step, models=[self], optimizers=[self._optimizer],
            bucket=self._bucket_buckets is not None,
            buckets=self._bucket_buckets, plan=self._mesh_plan,
            remat=remat)
        self._train_step_split = False

    def eval_batch(self, inputs, labels=None):
        """reference hapi/model.py:eval_batch — loss + metric updates."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else \
            ([] if labels is None else [labels])
        if self._eval_fn is None:
            def ev(*args):
                n_in = len(inputs)
                ins, labs = args[:n_in], args[n_in:]
                was = self.training
                self.eval()
                try:
                    outs = self(*ins)
                finally:
                    if was:
                        self.train()
                outs_l = outs if isinstance(outs, (list, tuple)) else \
                    [outs]
                loss = self._compute_loss(outs, list(labs)) \
                    if self._loss else None
                return outs_l[0], loss
            self._eval_fn = jit.to_static(ev, models=[self])
        from ..tensor import to_tensor
        args = [to_tensor(a) for a in list(inputs) + list(labels)]
        out0, loss = self._eval_fn(*args)
        if self._metrics and len(args) > len(inputs):
            for m in self._metrics:
                extra = m.add_metric_op(out0, args[len(inputs)])
                m.update(*extra)
        return [0.0 if loss is None else float(np.asarray(loss.numpy()))]

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._pred_fn is None:
            def pr(*ins):
                was = self.training
                self.eval()
                try:
                    return self(*ins)
                finally:
                    if was:
                        self.train()
            self._pred_fn = jit.to_static(pr, models=[self])
        from ..tensor import to_tensor
        outs = self._pred_fn(*[to_tensor(a) for a in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return [np.asarray(o.numpy()) for o in outs]

    # -- loops -------------------------------------------------------------

    def _loader(self, data, batch_size, shuffle, num_workers,
                drop_last=False):
        from ..io import DataLoader
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data  # already an iterable of batches
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, prefetch=0, bucket=False, checkpoint=None,
            save_steps=None, auto_resume=False, nan_guard=None,
            watchdog=None, metrics_port=None, grad_sync=None,
            flat_arena=None, mesh_plan=None, memory=None):
        """reference hapi/model.py:1128 fit.

        TPU pipelining extensions: ``prefetch=N`` stages the next N
        batches on device (background jax.device_put thread) while the
        current step runs; ``bucket=True`` pads the ragged final batch of
        each epoch up to ``batch_size`` so the compiled train step is
        reused instead of recompiled (padded rows repeat the last real
        sample and contribute to that batch's loss — prefer
        ``drop_last=True`` when exact epoch-tail losses matter).

        Resilience extensions (paddle_tpu.resilience): ``checkpoint``
        (an io.CheckpointManager or directory path) enables atomic
        model+optimizer checkpoints every ``save_steps`` global steps
        and on SIGTERM/SIGINT (cooperative preemption: the signal sets a
        flag, the loop saves at the next step boundary and stops);
        ``auto_resume=True`` restores the newest *valid* checkpoint and
        fast-forwards past already-trained batches; ``nan_guard`` (a
        resilience.NaNGuard or one of its policy strings) drops
        non-finite update steps inside the compiled train step and
        applies skip/rollback/raise on the host; ``watchdog`` (True or a
        resilience.Watchdog) flags steps that exceed a rolling
        p99-based deadline and dumps monitor state.

        Telemetry extension: ``metrics_port`` starts the live HTTP
        telemetry plane (``monitor.serve``) before the first step —
        ``/metrics`` (OpenMetrics), ``/healthz`` (watchdog/NaN-guard
        state), ``/snapshot``; use 0 for an ephemeral port
        (``monitor.export.port()`` reports it). The server outlives
        fit() — ``monitor.disable()`` tears it down.

        Communication extension: ``grad_sync``
        ("exact"|"quantized"|"overlap", or a
        parallel.overlap.GradSyncScheduler) attaches a gradient-sync
        scheduler to the optimizer — see docs/performance.md
        "Communication overlap & quantized sync" for what each mode
        means at this (GSPMD-synced) level vs explicit-DDP loops.
        ``flat_arena=True`` turns on the zero-copy flat parameter arena
        for the prepared Adam/AdamW (docs/performance.md "Flat
        parameter arena").

        Parallelism extension: ``mesh_plan`` (a
        parallel.planner.MeshPlan, a tuple of ``(regex, spec)`` rules,
        or ``"auto"``) places every parameter and optimizer slot under
        the plan's PartitionSpecs, shards input batches over the
        plan's data axes, and folds the plan key into the train step's
        executable cache key — one config line for dp×tp(×sp) hybrid
        layouts (docs/parallelism.md).

        Memory extension: ``memory`` (``"none"|"dots"|"full"``, a tuple
        of ``(regex, policy)`` rules, ``"offload"``, a dict like
        ``{"remat": "full", "offload": True, "master_weights": True}``,
        a memory_plan.MemoryPolicy, or ``"auto"``) installs a memory
        policy on the train step: rematerialization via jax.checkpoint,
        optimizer-state host offload (double-buffered, overlapped with
        fwd/bwd), and bf16 device params over fp32 master weights.
        ``"auto"`` compiles the baseline once, reads the predicted-peak
        model (monitor.memory.simulate) and picks the cheapest policy
        that fits ``device_hbm_limit()`` — see docs/performance.md
        "Memory as a planned resource"."""
        assert self._optimizer is not None, "call prepare() first"
        if grad_sync is not None:
            self._optimizer.set_grad_sync(grad_sync)
        if flat_arena is not None:
            self._optimizer.set_flat_arena(flat_arena)
        if mesh_plan is not None:
            from ..parallel import planner as _planner
            new_plan = _planner.resolve(mesh_plan)
            old_key = (self._mesh_plan.plan_key()
                       if self._mesh_plan is not None else None)
            if new_plan.plan_key() != old_key:
                self._train_step = None  # never reuse a stale layout
            self._mesh_plan = new_plan
            new_plan.place_model(self)
            new_plan.place_optimizer(self._optimizer)
        if memory is not None:
            from .. import memory_plan as _mp
            new_mem = _mp.resolve(memory)
            if _mp.policy_key(new_mem) != _mp.policy_key(self._memory):
                self._train_step = None  # policy change: one recompile
            self._apply_memory_policy(new_mem)
        from ..resilience import faults as _faults
        from ..resilience._common import record as _rrecord

        cm = None
        if checkpoint is not None:
            from ..io import CheckpointManager
            cm = (checkpoint if isinstance(checkpoint, CheckpointManager)
                  else CheckpointManager(checkpoint))
        if isinstance(nan_guard, str):
            from ..resilience.guard import NaNGuard
            nan_guard = NaNGuard(nan_guard, checkpoint_manager=cm)
        if nan_guard is not None and nan_guard.checkpoint_manager is None:
            nan_guard.checkpoint_manager = cm
        # the guard's where-selects are baked into the traced step, so
        # flipping guard presence must invalidate the compiled step
        if (nan_guard is not None) != self._guard_traced:
            self._guard_traced = nan_guard is not None
            self._train_step = None
        wd = None
        if watchdog is not None and watchdog is not False:
            from ..resilience.watchdog import Watchdog
            wd = watchdog if isinstance(watchdog, Watchdog) else Watchdog()
        if metrics_port is not None:
            _monitor.serve(port=metrics_port)

        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        buckets = [batch_size] if bucket else None
        if buckets != self._bucket_buckets:
            self._bucket_buckets = buckets
            self._train_step = None  # recompile with/without bucketing
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cblist = CallbackList(cbs, self, {
            "epochs": epochs, "verbose": verbose, "metrics":
            ["loss"] + [m.name() for m in self._metrics]})
        self.stop_training = False

        start_step = 0
        if auto_resume and cm is not None:
            latest = cm.latest_step()
            if latest is not None:
                state = cm.restore(model=self, optimizer=self._optimizer)
                start_step = int(state.get("step", latest)) + 1
                self._train_step = None  # recompile against restored state
                _rrecord("auto_resume", step=start_step,
                         checkpoint_step=latest, where="fit")
        handler = None
        if cm is not None:
            from ..resilience.preempt import PreemptionHandler
            handler = PreemptionHandler().install()
            # arm the signal-path flush: a real SIGTERM saves the last
            # completed step immediately, in case the grace window ends
            # before this loop reaches its next boundary
            handler.attach(cm, save_fn=lambda s: cm.save(
                s, model=self, optimizer=self._optimizer))
        if nan_guard is not None:
            nan_guard.install()
        if wd is not None:
            wd.start()

        cblist.call("on_train_begin")
        history = {"loss": []}
        global_step = 0
        try:
            for epoch in range(epochs):
                cblist.call("on_epoch_begin", epoch)
                self.train()
                losses = []
                src = pio.prefetch_to_device(iter(loader), size=prefetch) \
                    if prefetch else loader
                for step, batch in enumerate(src):
                    if global_step < start_step:
                        global_step += 1  # auto_resume fast-forward
                        continue
                    cblist.call("on_train_batch_begin", step)
                    ins, labs = self._split_batch(batch)
                    if _faults.enabled():
                        _faults.maybe_raise("host_loss", global_step)
                        if _faults.fire("nan_grad", global_step):
                            ins = [self._poison(ins[0])] + list(ins[1:])
                    wd_ctx = wd.step(global_step) if wd is not None else None
                    try:
                        if wd_ctx is not None:
                            wd_ctx.__enter__()
                        if _faults.enabled():
                            _faults.maybe_sleep("slow_step", global_step)
                        # the step-loop span: runs on the main thread,
                        # overlapping prefetch.produce spans on the
                        # producer track when prefetch= is on
                        with _monitor.trace.span("fit.step",
                                                 step=global_step):
                            (loss,) = self.train_batch(ins, labs)
                    finally:
                        if wd_ctx is not None:
                            wd_ctx.__exit__(None, None, None)
                    if self._memory == "auto":
                        # the first batch compiled the baseline and left
                        # its aot capture in the monitor ledger — pick
                        # the policy now, recompile (once) on the next
                        # batch under the pick
                        self._finish_auto_memory()
                    ok = True
                    if nan_guard is not None:
                        ok = nan_guard.check_host(
                            loss, step=global_step, model=self,
                            optimizer=self._optimizer, where="fit")
                        if not ok and \
                                nan_guard.policy == "rollback_to_last_ckpt":
                            # restored state: retrace on the next batch
                            self._train_step = None
                    if ok:
                        losses.append(loss)
                    if handler is not None:
                        handler.notify_step(global_step)
                    cblist.call("on_train_batch_end", step, {
                        "loss": loss,
                        "batch_size": ins[0].shape[0] if hasattr(
                            ins[0], "shape") else 1})
                    preempted = (handler is not None and handler.triggered) \
                        or (_faults.enabled() and
                            _faults.fire("preempt", global_step))
                    if cm is not None and (preempted or (
                            save_steps and
                            (global_step + 1) % save_steps == 0)) and (
                            handler is None or
                            handler.flushed_step != global_step):
                        # (a signal-path flush may already have saved
                        # exactly this step — don't save it twice)
                        cm.save(global_step, model=self,
                                optimizer=self._optimizer)
                        if preempted:
                            _rrecord("preempt_save", step=global_step,
                                     where="fit")
                    global_step += 1
                    if preempted:
                        self.stop_training = True
                        break
                logs = {"loss": float(np.mean(losses)) if losses else 0.0}
                if eval_data is not None and (epoch + 1) % eval_freq == 0 \
                        and not self.stop_training:
                    eres = self.evaluate(eval_data, batch_size=batch_size,
                                         verbose=0)
                    # eval metrics get an eval_ prefix so the train loss is
                    # not silently overwritten in logs/history
                    logs.update({f"eval_{k}": v for k, v in eres.items()})
                history["loss"].append(logs["loss"])
                cblist.call("on_epoch_end", epoch, logs)
                if self.stop_training:
                    break
        except BaseException as e:
            # unhandled crash in the train loop: leave a flight-recorder
            # dump (last spans + counters + active HLO) then re-raise.
            # OOM-shaped errors route through the memory postmortem so
            # the bundle includes the ranked contributor ledger.
            if _monitor.enabled():
                if not _monitor.memory.handle_oom(e, where="fit",
                                                  step=global_step):
                    _monitor.trace.flight_record("fit_crash",
                                                 step=global_step)
            raise
        finally:
            if wd is not None:
                wd.stop()
            if nan_guard is not None:
                nan_guard.uninstall()
            if handler is not None:
                handler.uninstall()
        cblist.call("on_train_end", {"loss": history["loss"]})
        return history

    def _apply_memory_policy(self, pol):
        """Install a resolved memory policy (MemoryPolicy or "auto"),
        detaching mechanisms the new policy drops: a toggle away from
        offload materialises the arena back on device and stops the
        worker; a toggle away from master_weights clears the bf16 view
        dtype (the arena itself stays — it is still exact fp32)."""
        from ..memory_plan import MemoryPolicy, detach_offload
        self._memory = pol
        opt = self._optimizer
        if opt is None:
            return
        if not (isinstance(pol, MemoryPolicy) and pol.offload) and \
                getattr(opt, "_offloader", None) is not None:
            detach_offload(opt)
            self._train_step_split = False
        if not (isinstance(pol, MemoryPolicy) and pol.master_weights):
            opt._arena_view_dtype = None

    def _finish_auto_memory(self):
        """memory="auto" deferral: the baseline step just compiled, so
        monitor.memory.simulate() now has an HLO to cost. Pick the
        cheapest policy that fits the HBM budget and install it; if it
        differs from the baseline the next batch recompiles exactly
        once."""
        from .. import memory_plan as _mp
        if not _monitor.enabled():
            import warnings
            warnings.warn(
                'memory="auto" needs the monitor enabled (the compiled '
                "step's aot capture feeds the predicted-peak model); "
                "keeping the baseline policy", RuntimeWarning)
            self._memory = None
            return
        decision = _mp.plan_memory(auto=True)
        pol = decision["policy"]
        if _mp.policy_key(pol) != "none":
            self._train_step = None  # recompile under the pick
        self._apply_memory_policy(pol)

    @staticmethod
    def _poison(a):
        """nan_grad fault: replace a batch input with NaNs (same
        shape/dtype so the compiled step is reused, not recompiled)."""
        arr = np.asarray(a.numpy() if hasattr(a, "numpy") else a)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return arr

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        """reference hapi/model.py:1337 evaluate."""
        loader = self._loader(eval_data, batch_size, False, num_workers)
        for m in self._metrics:
            m.reset()
        cblist = CallbackList(list(callbacks or []) + (
            [ProgBarLogger(log_freq, verbose)] if verbose else []),
            self, {})
        cblist.call("on_eval_begin")
        losses = []
        for step, batch in enumerate(loader):
            cblist.call("on_eval_batch_begin", step)
            ins, labs = self._split_batch(batch)
            (loss,) = self.eval_batch(ins, labs)
            losses.append(loss)
            cblist.call("on_eval_batch_end", step, {"loss": loss})
        res = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                res.update(dict(zip(name, acc)))
            else:
                res[name] = acc
        cblist.call("on_eval_end", res)
        return res

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False):
        """reference hapi/model.py predict."""
        loader = self._loader(test_data, batch_size, False, num_workers)
        outs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outs.append(self.predict_batch(ins))
        if stack_outputs:
            n = len(outs[0])
            return [np.concatenate([o[i] for o in outs]) for i in range(n)]
        return outs

    # -- persistence -------------------------------------------------------

    def save(self, path):
        """reference hapi/model.py:862 save — .pdparams + .pdopt."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        pio.save(self.state_dict(), path + ".pdparams")
        if self._optimizer is not None and hasattr(self._optimizer,
                                                   "state_dict"):
            pio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        """reference hapi/model.py:907 load."""
        state = pio.load(path + ".pdparams")
        self.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)
                and hasattr(self._optimizer, "set_state_dict")):
            self._optimizer.set_state_dict(pio.load(opt_path))
        self._train_step = None  # recompile against restored state

    def parameters(self, *a, **kw):
        return super().parameters(*a, **kw)

    def summary(self, input_size=None, dtype=None):
        """Param-count summary (reference hapi model_summary)."""
        rows = []
        total = 0
        for name, p in self.named_parameters():
            n = int(p.data.size)
            total += n
            rows.append(f"{name:<44s} {str(tuple(p.data.shape)):<18s} {n:>12,d}")
        table = "\n".join(rows + ["-" * 76,
                                  f"total trainable params: {total:,}"])
        print(table)
        return {"total_params": total}
