"""hapi.download (reference: incubate/hapi/download.py —
get_weights_path_from_url / get_path_from_url with a ~/.cache dir and md5
checks).

This build environment has ZERO network egress, so the download step is
redesigned rather than stubbed: URLs resolve through the local cache
only (shared derivation with dataset.common — one DATA_HOME, one md5
helper). A file already present (same basename, optional md5 match) is
returned; otherwise the error says exactly where to drop the file —
which is also the sane behavior for air-gapped TPU pods."""
from __future__ import annotations

import os
import os.path as osp

from ..dataset import common as _common
from ..dataset.common import md5file

__all__ = ["get_weights_path_from_url", "get_path_from_url", "DATA_HOME"]

# ONE env var governs both cache roots: when PADDLE_TPU_DATA_HOME is set
# it IS the root for hapi (and dataset.common uses it as its dataset
# dir); unset, both default under ~/.cache/paddle_tpu
_env_home = os.environ.get("PADDLE_TPU_DATA_HOME")
DATA_HOME = osp.expanduser(_env_home) if _env_home \
    else osp.dirname(_common.DATA_HOME)


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    """Resolve `url` to a local file under root_dir (default DATA_HOME).
    Never touches the network: the file must already be in the cache (put
    there by your data-prep pipeline). check_exist=False skips the md5
    validation of an already-cached file (reference semantics)."""
    root_dir = osp.expanduser(root_dir) if root_dir else DATA_HOME
    fname = osp.basename(url.rstrip("/")) or "download"
    path = osp.join(root_dir, fname)
    if osp.exists(url):  # a local path was passed directly
        return url
    if osp.exists(path):
        if not check_exist or md5sum is None or md5file(path) == md5sum:
            return path
        raise ValueError(
            f"cached file {path} exists but its md5 does not match "
            f"{md5sum} — replace the corrupt/stale file (source: {url})")
    raise FileNotFoundError(
        f"'{fname}' not found in the local cache ({root_dir}) and this "
        "environment has no network egress. Place the file at "
        f"{path} (source: {url}).")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, osp.join(DATA_HOME, "weights"), md5sum)
