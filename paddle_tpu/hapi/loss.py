"""hapi losses (reference: incubate/hapi/loss.py:Loss/CrossEntropy/
SoftmaxWithCrossEntropy)."""
from __future__ import annotations

from ..ops import loss as L


class Loss:
    """reference hapi/loss.py:Loss — maps (outputs, labels) -> scalar."""

    def __init__(self, average=True):
        self.average = average

    def forward(self, outputs, labels):
        raise NotImplementedError

    def __call__(self, outputs, labels):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        outputs = outputs if isinstance(outputs, (list, tuple)) else \
            [outputs]
        losses = self.forward(list(outputs), list(labels))
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        if self.average:
            losses = [lo.mean() for lo in losses]
        else:
            losses = [lo.sum() for lo in losses]
        return losses


class CrossEntropy(Loss):
    """reference hapi/loss.py:CrossEntropy — softmax CE on logits."""

    def forward(self, outputs, labels):
        return [L.cross_entropy(o, lb, reduction="none")
                for o, lb in zip(outputs, labels)]


class SoftmaxWithCrossEntropy(Loss):
    """reference hapi/loss.py:SoftmaxWithCrossEntropy (fused kernel on
    the TPU path via ops.loss's pallas gate)."""

    def forward(self, outputs, labels):
        return [L.softmax_with_cross_entropy(o, lb)
                for o, lb in zip(outputs, labels)]
