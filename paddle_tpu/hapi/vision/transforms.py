"""hapi.vision.transforms — composable image preprocessing (reference:
the hapi generation's vision transforms used with DatasetFolder; this
paddle generation shipped them beside incubate/hapi — rebuilt here as
pure-numpy callables).

Design: every transform is HOST-side numpy over HWC images (uint8 or
float). That is deliberate: decode/augment is the GIL-bound work
io.DataLoader's worker PROCESSES parallelize (num_workers>0), and the
device should receive small uint8 batches (4x cheaper over the
host-to-device link) and normalize on-chip inside the jitted step —
compose Normalize into the model input when feeding uint8, or into the
transform chain when CPU cycles are free."""
from __future__ import annotations

import numbers

import numpy as np

from ...dataset import image as _img

__all__ = ["Compose", "Resize", "CenterCrop", "RandomCrop",
           "RandomHorizontalFlip", "RandomVerticalFlip",
           "RandomResizedCrop", "Normalize", "Transpose", "ToTensor",
           "BrightnessTransform", "Lambda"]


def _pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


class Compose:
    """Chain transforms: Compose([Resize(256), RandomCrop(224), ...])."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img

    def __repr__(self):
        inner = ", ".join(type(t).__name__ for t in self.transforms)
        return f"Compose([{inner}])"


class Resize:
    """Resize so the SHORT side equals `size` (int, aspect preserved) or
    to exact (h, w)."""

    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        img = np.asarray(img)
        if isinstance(self.size, numbers.Number):
            return _img.resize_short(img, int(self.size))
        h, w = _pair(self.size)
        return _img.resize_exact(img, h, w)


def _check_crop(img, ch, cw, kind):
    h, w = img.shape[:2]
    if h < ch or w < cw:
        raise ValueError(
            f"{kind}({ch}, {cw}) on a {h}x{w} image — the input is "
            "smaller than the crop (an undersized sample would crash "
            "batch collation downstream); Resize first")


class CenterCrop:
    def __init__(self, size):
        self.size = _pair(size)

    def __call__(self, img):
        img = np.asarray(img)
        ch, cw = self.size
        _check_crop(img, ch, cw, "CenterCrop")
        h, w = img.shape[:2]
        top = (h - ch) // 2
        left = (w - cw) // 2
        return img[top:top + ch, left:left + cw]


class RandomCrop:
    def __init__(self, size, rng=None):
        self.size = _pair(size)
        self.rng = rng or np.random

    def __call__(self, img):
        img = np.asarray(img)
        ch, cw = self.size
        _check_crop(img, ch, cw, "RandomCrop")
        h, w = img.shape[:2]
        top = self.rng.randint(0, h - ch + 1)
        left = self.rng.randint(0, w - cw + 1)
        return img[top:top + ch, left:left + cw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, rng=None):
        self.prob = prob
        self.rng = rng or np.random

    def __call__(self, img):
        if self.rng.rand() < self.prob:
            return np.asarray(img)[:, ::-1]
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, rng=None):
        self.prob = prob
        self.rng = rng or np.random

    def __call__(self, img):
        if self.rng.rand() < self.prob:
            return np.asarray(img)[::-1]
        return np.asarray(img)


class RandomResizedCrop:
    """Random area/aspect crop then resize to `size` — the ImageNet
    training crop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 rng=None):
        self.size = _pair(size)
        self.scale = scale
        self.ratio = ratio
        self.rng = rng or np.random

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * self.rng.uniform(*self.scale)
            aspect = self.rng.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * aspect)))
            ch = int(round(np.sqrt(target / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = self.rng.randint(0, h - ch + 1)
                left = self.rng.randint(0, w - cw + 1)
                crop = img[top:top + ch, left:left + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(img))


class Normalize:
    """(img - mean) / std, channel-last by default; outputs float32."""

    def __init__(self, mean, std, channel_axis=-1):
        self.mean = np.asarray(mean, "float32")
        self.std = np.asarray(std, "float32")
        self.channel_axis = channel_axis

    def __call__(self, img):
        img = np.asarray(img, "float32")
        shape = [1] * img.ndim
        shape[self.channel_axis] = -1
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Transpose:
    """HWC -> CHW (the zoo models' NCHW input layout)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class ToTensor:
    """uint8 HWC -> float32 CHW in [0, 1]."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype("float32") / 255.0
        return img.transpose(2, 0, 1) if img.ndim == 3 else img


class BrightnessTransform:
    def __init__(self, value, rng=None):
        self.value = float(value)
        self.rng = rng or np.random

    def __call__(self, img):
        img = np.asarray(img)
        alpha = 1.0 + self.rng.uniform(-self.value, self.value)
        out = img.astype("float32") * alpha
        # value range follows DTYPE: uint8 clips at [0, 255]; float
        # images carry arbitrary ranges ([-1,1] MNIST, 0-255 decoded
        # floats) and are NOT clipped — the caller's Normalize defines
        # their range
        if img.dtype == np.uint8:
            out = np.clip(out, 0.0, 255.0)
        return out


class Lambda:
    def __init__(self, fn):
        self.fn = fn

    def __call__(self, img):
        return self.fn(img)
