"""hapi.vision (reference: incubate/hapi/vision — the models package;
transforms shipped beside this generation's hapi and are rebuilt in
transforms.py)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
