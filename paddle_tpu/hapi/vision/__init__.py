"""hapi.vision (reference: incubate/hapi/vision — the models package;
transforms arrived in later generations)."""
from . import models  # noqa: F401
