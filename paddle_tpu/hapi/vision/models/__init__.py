"""hapi.vision.models (reference: incubate/hapi/vision/models — LeNet in
this generation; the wider zoo lives in paddle_tpu.models)."""
from ....models.lenet import LeNet  # noqa: F401

__all__ = ["LeNet"]
