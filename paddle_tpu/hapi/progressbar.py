"""hapi.progressbar (reference: incubate/hapi/progressbar.py —
the Keras-style training bar the hapi callbacks drive)."""
from __future__ import annotations

import sys
import time

__all__ = ["ProgressBar"]


class ProgressBar:
    """num: total steps (None = unknown/stream mode). update(i, values)
    renders `i/num [====>...] - metric: v` to the stream."""

    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=None):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._file = file  # None = live sys.stdout at write time
        self._start = time.time() if start else None
        self._last_len = 0

    @property
    def _out(self):
        return self._file if self._file is not None else sys.stdout

    def start(self):
        self._start = time.time()

    def _format_values(self, values):
        out = []
        for k, v in values:
            if isinstance(v, (int, float)):
                out.append(f"{k}: {v:.4f}")
            else:
                out.append(f"{k}: {v}")
        return " - ".join(out)

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        values = values or []
        if self._num:
            frac = min(current_num / self._num, 1.0)
            filled = int(frac * self._width)
            bar = "=" * max(filled - 1, 0)
            bar += ">" if filled < self._width else "="
            bar = bar.ljust(self._width, ".")
            line = f"step {current_num}/{self._num} [{bar}]"
        else:
            line = f"step {current_num}"
        metrics = self._format_values(values)
        if metrics:
            line += " - " + metrics
        if self._start is None:  # start=False: timer begins at first tick
            self._start = time.time()
        elapsed = time.time() - self._start
        line += f" - {1000 * elapsed / max(current_num, 1):.0f}ms/step"
        if self._verbose == 1:
            pad = max(self._last_len - len(line), 0)
            self._out.write("\r" + line + " " * pad)
            if self._num and current_num >= self._num:
                self._out.write("\n")
            self._last_len = len(line)
        else:
            self._out.write(line + "\n")
        self._out.flush()
