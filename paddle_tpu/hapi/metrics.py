"""hapi metrics (reference: incubate/hapi/metrics.py:Metric/Accuracy)."""
from __future__ import annotations

import numpy as np


class Metric:
    """reference hapi/metrics.py:Metric — reset/update/accumulate/name."""

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return getattr(self, "_name", type(self).__name__.lower())

    def add_metric_op(self, *args):
        """Pre-process (pred, label) inside the compiled step; default
        passthrough."""
        return args


class Accuracy(Metric):
    """reference hapi/metrics.py:Accuracy — top-k accuracy."""

    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def add_metric_op(self, pred, label):
        import jax.numpy as jnp
        from ..tensor import Tensor
        p = pred.data if hasattr(pred, "data") else pred
        lb = label.data if hasattr(label, "data") else label
        if lb.ndim == p.ndim and lb.shape[-1] == 1:
            lb = lb[..., 0]
        kk = min(self.maxk, p.shape[-1])
        top = jnp.argsort(p, axis=-1)[..., ::-1][..., :kk]
        correct = (top == lb[..., None]).astype(jnp.float32)
        return (Tensor(correct),)

    def update(self, correct):
        c = np.asarray(correct.numpy() if hasattr(correct, "numpy")
                       else correct)
        n = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += n
        return self.total[0] / max(self.count[0], 1)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]
