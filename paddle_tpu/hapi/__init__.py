"""paddle_tpu.hapi — the high-level Model API.

TPU-native rebuild of reference python/paddle/incubate/hapi: Model
(prepare/fit/evaluate/predict/save/load), callbacks, hapi losses and
metrics. The train/eval steps compile to single donated XLA executables
via jit.to_static, so `fit` runs one fused computation per batch.
"""
from .model import Model, Input, set_device  # noqa: F401
from .callbacks import (Callback, ProgBarLogger, ModelCheckpoint,  # noqa
                        EarlyStopping)
from .loss import Loss, CrossEntropy, SoftmaxWithCrossEntropy  # noqa: F401
from .metrics import Metric, Accuracy  # noqa: F401
from . import model  # noqa: F401
from . import callbacks  # noqa: F401
from . import loss  # noqa: F401
from . import metrics  # noqa: F401
from . import distributed  # noqa: F401,E402
from .distributed import DistributedBatchSampler  # noqa: F401,E402
from . import datasets  # noqa: F401,E402
from . import download  # noqa: F401,E402
from .download import get_weights_path_from_url  # noqa: F401,E402
from . import progressbar  # noqa: F401,E402
from . import vision  # noqa: F401,E402
