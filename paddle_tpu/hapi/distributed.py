"""hapi.distributed (reference:
python/paddle/incubate/hapi/distributed.py:36 DistributedBatchSampler).

TPU note: with the GSPMD path a GLOBAL batch is usually placed with
`fleet.shard_batch` and XLA splits it over dp — but per-process input
pipelines (multi-host, or io.DataLoader feeding per-replica shards) still
want the reference's rank-exclusive sampler, so it is kept behaviorally
identical: pad indices to a multiple of nranks, optional epoch-seeded
shuffle, contiguous per-rank subsample, set_epoch for reshuffling."""
from __future__ import annotations

import math

import numpy as np

from ..io import BatchSampler
from ..parallel.env import ParallelEnv

__all__ = ["DistributedBatchSampler"]


class DistributedBatchSampler(BatchSampler):
    def __init__(self, dataset, batch_size, shuffle=False, drop_last=False,
                 num_replicas=None, rank=None):
        if not (isinstance(batch_size, int) and batch_size > 0):
            raise ValueError("batch_size should be a positive integer")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = bool(shuffle)
        self.drop_last = bool(drop_last)
        env = ParallelEnv()
        self.nranks = num_replicas if num_replicas is not None \
            else env.world_size
        self.local_rank = rank if rank is not None else env.rank
        self.epoch = 0
        self.num_samples = int(
            math.ceil(len(dataset) * 1.0 / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if n == 0:
            return
        indices = list(range(n))
        while len(indices) < self.total_size:  # pad to a rank multiple
            indices += indices[:self.total_size - len(indices)]
        if self.shuffle:
            np.random.RandomState(self.epoch).shuffle(indices)
            self.epoch += 1
        # contiguous per-rank slice (reference subsampling)
        start = self.local_rank * self.num_samples
        indices = indices[start:start + self.num_samples]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return int(math.ceil(self.num_samples / self.batch_size))

    def set_epoch(self, epoch):
        self.epoch = epoch
