"""paddle_tpu.jit — dygraph-to-static compilation (the TPU perf path).

TPU-native rebuild of the reference's @to_static / ProgramTranslator
(reference: python/paddle/fluid/dygraph/dygraph_to_static/* and jit.py).
The reference rewrites Python AST into a static Program; on TPU we do
something far simpler and stronger: functionalize the *state* and let
`jax.jit` trace the ordinary dygraph code into one XLA computation.

How it works: all mutable framework state (Parameters, buffers, optimizer
slots, lr, the global PRNG key) lives in Tensors. ``to_static(fn)`` swaps
every such Tensor's payload for a traced value, runs ``fn`` (the tape
records vjps on tracers; ``loss.backward()`` and ``optimizer.step()``
mutate traced payloads), then returns (outputs, new_state) from the traced
function. The result: forward + backward + optimizer update fused into a
single donated-buffer XLA executable — the shape the MXU wants.

State discovery: pass ``models=``/``optimizers=`` explicitly, or let
to_static scan the function's closure for Layers and Optimizers.
"""
from __future__ import annotations

import functools
import inspect

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor, Parameter
from .nn.layer import Layer
from .optimizer import Optimizer
from . import random as prandom
from . import monitor as _monitor


def _discover_state_objects(fn, models, optimizers, scalers=None):
    from .amp import GradScaler
    models = list(models) if models else []
    optimizers = list(optimizers) if optimizers else []
    scalers = list(scalers) if scalers else []
    seen_m = {id(m) for m in models}
    seen_o = {id(o) for o in optimizers}
    seen_s = {id(s) for s in scalers}

    def _is_optimizer(obj):
        # a fleet.DistributedOptimizer duck-types Optimizer around `inner`
        return isinstance(obj, Optimizer) or isinstance(
            getattr(obj, "inner", None), Optimizer)

    def visit(obj):
        if isinstance(obj, Layer) and id(obj) not in seen_m:
            seen_m.add(id(obj))
            models.append(obj)
        elif _is_optimizer(obj) and id(obj) not in seen_o:
            seen_o.add(id(obj))
            optimizers.append(obj)
        elif isinstance(obj, GradScaler) and id(obj) not in seen_s:
            seen_s.add(id(obj))
            scalers.append(obj)

    target = fn
    while hasattr(target, "__wrapped__"):
        target = target.__wrapped__
    if inspect.ismethod(target):
        visit(target.__self__)
        target = target.__func__
    if getattr(target, "__closure__", None):
        for cell in target.__closure__:
            try:
                visit(cell.cell_contents)
            except ValueError:
                pass
    return models, optimizers, scalers


def _collect_state(models, optimizers, scalers=()):
    """Name → Tensor holder map for everything the step may read/mutate."""
    holders = {}
    # optimizers first: a flat-arena optimizer carries its trainables in
    # one flat buffer per dtype — those params are traced THROUGH the
    # arena (views sliced from the flat tracer), not as separate holders
    covered = set()
    for oi, o in enumerate(optimizers):
        o._ensure_all_slots()
        holders[f"o{oi}.lr"] = o._lr_tensor
        for pid, slots in o._accumulators.items():
            for sname, t in slots.items():
                holders[f"o{oi}.{pid}.{sname}"] = t
        arena = getattr(o, "_arena", None)
        if arena is not None:
            covered |= arena.param_ids
    for mi, m in enumerate(models):
        for name, p in m.named_parameters():
            if id(p) not in covered:
                holders[f"m{mi}.{name}"] = p
        for name, b in m.named_buffers():
            if isinstance(b, Tensor):
                holders[f"m{mi}.buf.{name}"] = b
    for si, s in enumerate(scalers):
        holders[f"s{si}.scale"] = s._scale
        holders[f"s{si}.good"] = s._good
        holders[f"s{si}.bad"] = s._bad
    holders["rng"] = prandom.global_key_tensor()
    return holders


class StaticFunction:
    """The compiled callable returned by to_static."""

    def __init__(self, fn, models=None, optimizers=None, donate_state=True,
                 jit_kwargs=None, scalers=None, bucket=False, buckets=None,
                 pad_mode="repeat", plan=None, remat=None):
        functools.update_wrapper(self, fn,
                                 assigned=("__name__", "__doc__"),
                                 updated=())
        # AST pass (reference: ProgramTranslator): converted lazily at
        # call time so ProgramTranslator.enable() flips apply dynamically
        self._orig_fn = fn
        self._converted_fn = None
        self._fn = fn
        self._models = models
        self._optimizers = optimizers
        self._scalers = scalers
        self._donate = donate_state
        self._jit_kwargs = jit_kwargs or {}
        self._cache = {}
        self._state_cache = None  # (validity key, holders, names, params)
        # shape bucketing: ragged leading (batch) dims round up to a
        # bucket so a short final batch reuses the full-batch executable
        self._bucket = bucket
        self._buckets = buckets
        self._pad_mode = pad_mode
        # parallel.planner.MeshPlan: input batches shard under the
        # plan's data spec and the plan key joins the cache key (a plan
        # switch can never silently reuse a stale executable)
        self._plan = plan
        # memory_plan remat policy: layers called inside the traced body
        # checkpoint under this ambient policy; the canonical key joins
        # the cache key so a policy toggle is exactly one recompile
        if remat is not None:
            from . import memory_plan as _mp
            remat = _mp._canon_remat(remat)
        self._remat = remat
        self._seen_base = set()  # recompile (vs first-compile) accounting

    def _resolve_objects(self):
        if self._models is None or self._optimizers is None:
            m, o, s = _discover_state_objects(self._fn, self._models,
                                              self._optimizers,
                                              self._scalers)
            self._models, self._optimizers = m, o
            if self._scalers is None:
                self._scalers = s
        elif self._scalers is None:
            # models+optimizers given explicitly: discover ONLY scalers so
            # closure objects the caller chose to exclude stay excluded
            _, _, s = _discover_state_objects(self._fn, self._models,
                                              self._optimizers, None)
            self._scalers = s
        return self._models, self._optimizers, self._scalers

    def _cached_state(self, models, optimizers, scalers):
        """The name→holder map, cached across calls: holders are stable
        Tensor objects whose .data the step swaps, so re-walking
        named_parameters()/named_buffers() every call (~17ms on
        ResNet-50) only matters when structure actually changed. Cache
        validity = the global Layer structure version + per-optimizer
        accumulator-slot counts (slots are created lazily on first
        step) + each param's stop_gradient flag (unfreezing must force
        a re-collect so _ensure_all_slots builds the new slots)."""
        from .nn.layer import struct_version

        def vkey(params):
            return (struct_version(),
                    tuple(sum(len(s) for s in o._accumulators.values())
                          for o in optimizers),
                    tuple(p.stop_gradient for p in params))

        if self._state_cache is not None and self._state_cache[0] == \
                vkey(self._state_cache[3]):
            return self._state_cache[1], self._state_cache[2], \
                self._state_cache[3]
        holders = _collect_state(models, optimizers, scalers)
        state_names = sorted(holders)
        all_params = [p for m in models for p in m.parameters()]
        # _ensure_all_slots() inside _collect_state may have created
        # slots — snapshot the validity key AFTER collection
        self._state_cache = (vkey(all_params), holders, state_names,
                             all_params)
        return holders, state_names, all_params

    def __call__(self, *args, **kwargs):
        from .dygraph_to_static import ProgramTranslator, convert_function
        ast_on = ProgramTranslator.is_enabled()
        if ast_on:
            if self._converted_fn is None:
                self._converted_fn = convert_function(self._orig_fn)
            self._fn = self._converted_fn
        else:
            self._fn = self._orig_fn
        models, optimizers, scalers = self._resolve_objects()
        from . import tensor as _ptensor
        own_arenas = []
        if _ptensor._arena_hook is not None:
            from .optimizer import arena as _arena_mod
            own_arenas = [a for a in (getattr(o, "_arena", None)
                                      for o in optimizers) if a is not None]
            # external writes to arena leaves (set_value/checkpoint
            # restore) must land in the flat buffers before we trace
            # from them; foreign arenas also sync so the step reads
            # fresh leaf data
            _arena_mod.flush(exclude=own_arenas)
        holders, state_names, all_params = self._cached_state(
            models, optimizers, scalers)

        # Tensor is a pytree node, so leaves here are raw arrays / scalars.
        flat_args, treedef = jax.tree_util.tree_flatten((args, kwargs))
        arr_idx, arrays, statics = [], [], []
        for i, a in enumerate(flat_args):
            if isinstance(a, (jax.Array, np.ndarray)):
                arrays.append(jnp.asarray(a))
                arr_idx.append(i)
            else:
                statics.append((i, a))

        pad_info = None
        if self._bucket and arrays and arrays[0].ndim >= 1:
            # bucket the common leading (batch) dim: every array sharing
            # it pads up to the bucket; outputs slice back after the call
            from .io.bucketing import next_bucket, pad_to_bucket
            lead = arrays[0].shape[0]
            target = next_bucket(lead, self._buckets)
            if target != lead:
                arrays = [pad_to_bucket(a, target, mode=self._pad_mode)
                          if a.ndim >= 1 and a.shape[0] == lead else a
                          for a in arrays]
                pad_info = (lead, target)
                if _monitor.enabled():
                    _monitor.counter("jit.bucket_pad").inc()

        if self._plan is not None:
            arrays = [self._plan.shard_input(a) for a in arrays]

        train_flags = tuple(m.training for m in models)
        base = (treedef, tuple(arr_idx),
                tuple((i, repr(s)) for i, s in statics), train_flags,
                tuple(state_names), ast_on,
                self._plan.plan_key() if self._plan is not None else None,
                self._remat)
        key = base + (tuple((a.shape, str(a.dtype)) for a in arrays),)

        fn_label = getattr(self, "__name__", "fn")
        is_new = key not in self._cache
        if _monitor.enabled():
            if not is_new:
                _monitor.counter("jit.cache_hit").inc()
            else:
                _monitor.counter("jit.compile").inc()
                if base in self._seen_base:
                    _monitor.counter("jit.recompile").inc()
        if is_new:
            self._seen_base.add(base)
            with _monitor.trace.span(f"jit.compile.{fn_label}"):
                self._cache[key] = self._make_entry(treedef, arr_idx,
                                                    statics, state_names)
        entry = self._cache[key]

        state_vals = [holders[n].data for n in state_names]
        if is_new and _monitor.enabled():
            # AOT the fresh entry (the compile the first call pays
            # anyway) so monitor.xla records its measured flops/bytes;
            # any failure keeps the original jitted callable
            import time as _time
            _t0_compile = _time.perf_counter()
            with _monitor.trace.span("jit.aot_capture", fn=fn_label):
                entry["uncompiled"] = entry["jitted"]
                entry["jitted"] = _monitor.xla.aot_capture(
                    entry["jitted"], f"jit.{fn_label}",
                    (state_vals, arrays))
            # wall seconds the AOT compile cost — the goodput ledger's
            # compile category (monitor/step.py)
            _monitor.counter("jit.compile_s").inc(
                _time.perf_counter() - _t0_compile)
        with _monitor.trace.span(f"jit.{fn_label}"):
            try:
                out_arrays, new_state = entry["jitted"](state_vals, arrays)
            except ValueError:
                # an AOT Compiled is pinned to its capture-time input
                # shardings; when GSPMD's output sharding for a state
                # leaf drifts from its input one, the written-back state
                # no longer matches. Plain jax.jit reshards/recompiles
                # transparently — fall back to it so enabling the
                # monitor never changes trainability.
                fallback = entry.get("uncompiled")
                if fallback is None or fallback is entry["jitted"]:
                    raise
                entry["jitted"] = fallback
                if _monitor.enabled():
                    _monitor.counter("jit.aot_sharding_fallback").inc()
                out_arrays, new_state = entry["jitted"](state_vals, arrays)

        for name, new in zip(state_names, new_state):
            holders[name].data = new
        # the flat buffers just advanced; per-leaf views now lag until a
        # read syncs them (lazily — zero per-step scatter)
        for a in own_arenas:
            a.mark_stale()
        for p in all_params:
            p._grad = None

        if pad_info is not None:
            lead, target = pad_info
            out_arrays = [o[:lead] if getattr(o, "ndim", 0) >= 1 and
                          o.shape[0] == target else o
                          for o in out_arrays]

        # rebuild outputs: arrays -> Tensors at recorded positions
        meta = entry["meta"]
        out_leaves = []
        ai = 0
        for kind, payload in meta["slots"]:
            if kind == "arr":
                out_leaves.append(Tensor(out_arrays[ai]))
                ai += 1
            else:
                out_leaves.append(payload)
        return jax.tree_util.tree_unflatten(meta["treedef"], out_leaves)

    def _make_entry(self, treedef, arr_idx, statics, state_names):
        fn = self._fn
        fn_scope = getattr(self, "__name__", None) or "to_static"
        # a "root" scope is recognized by monitor.profile but never
        # counts as attribution — everything lives under it (cold path:
        # one dict write per compiled entry)
        _monitor.profile.register_scope(fn_scope, "root")
        models, optimizers = self._models, self._optimizers
        scalers = self._scalers or []
        meta = {}

        def traced(state_vals, arrays):
            flat = [None] * treedef.num_leaves
            for i, a in zip(arr_idx, arrays):
                flat[i] = a
            for i, s in statics:
                flat[i] = s
            args, kwargs = jax.tree_util.tree_unflatten(treedef, flat)

            hs = _collect_state(models, optimizers, scalers)
            arenas = [a for a in (getattr(o, "_arena", None)
                                  for o in optimizers) if a is not None]
            saved = {}
            saved_views = []
            try:
                for name, v in zip(state_names, state_vals):
                    saved[name] = hs[name].data
                    hs[name].data = v
                # arena-covered params: forward reads zero-copy views
                # sliced from the (now traced) flat buffers
                for a in arenas:
                    saved_views.append(a.bind_views())
                # tag the whole step's HLO with the function name (shows
                # up in XLA profiles / the flight recorder's HLO dump)
                if self._remat is not None:
                    from . import memory_plan as _mp
                    with _mp.remat_scope(self._remat):
                        with jax.named_scope(fn_scope):
                            out = fn(*args, **kwargs)
                else:
                    with jax.named_scope(fn_scope):
                        out = fn(*args, **kwargs)
                new_state = [hs[n].data for n in state_names]
                # flatten outputs treating Tensors as leaves (don't let the
                # pytree registration split them — we need to tag them)
                out_flat, out_treedef = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                slots, out_arrays = [], []
                for o in out_flat:
                    if isinstance(o, Tensor):
                        slots.append(("arr", None))
                        out_arrays.append(o.data)
                    elif isinstance(o, (jax.Array, np.ndarray)):
                        slots.append(("arr", None))
                        out_arrays.append(jnp.asarray(o))
                    else:
                        slots.append(("static", o))
                meta["slots"] = slots
                meta["treedef"] = out_treedef
                for m in models:
                    for p in m.parameters():
                        p._grad = None
                return out_arrays, new_state
            finally:
                for a, sv in zip(arenas, saved_views):
                    a.unbind_views(sv)
                for name, v in saved.items():
                    hs[name].data = v

        donate = (0,) if self._donate else ()
        jitted = jax.jit(traced, donate_argnums=donate, **self._jit_kwargs)
        return {"jitted": jitted, "meta": meta}


def to_static(function=None, input_spec=None, models=None, optimizers=None,
              donate_state=True, scalers=None, bucket=False, buckets=None,
              pad_mode="repeat", plan=None, remat=None, **kwargs):
    """Decorator/wrapper: compile a dygraph step into one XLA computation.

    reference: paddle.jit.to_static (dygraph_to_static/program_translator.py)
    — functional-state tracing, preceded by the AST pass
    (dygraph_to_static.convert_function) that rewrites tensor-dependent
    python `if`/`while` into lax control flow.

    ``bucket=True`` (+ ``buckets=[...]``) pads the arrays' common leading
    dim up to a bucket size before shape-keying, so ragged final batches
    reuse the full-batch executable instead of recompiling; array outputs
    at the bucket size are sliced back to the real length. Padded rows
    repeat the last real row (``pad_mode="zeros"`` zero-fills) and DO
    participate in scalar reductions — use io.bucketing.batch_mask in the
    loss when exact ragged-batch values matter.

    ``plan=`` (a parallel.planner.MeshPlan) shards input batches under
    the plan's data axes and folds the plan key into the executable
    cache key — switching plans recompiles instead of silently reusing
    a stale layout.

    ``remat=`` (memory_plan): activation rematerialization for the
    traced body — ``"dots"``/``"full"`` or ``((pattern, policy), ...)``
    per-layer rules. Layers called inside the step checkpoint under the
    ambient policy; the policy joins the cache key, so toggling it
    recompiles exactly once instead of silently reusing an executable
    with the wrong memory shape.
    """
    def wrap(fn):
        return StaticFunction(fn, models=models, optimizers=optimizers,
                              donate_state=donate_state, scalers=scalers,
                              bucket=bucket, buckets=buckets,
                              pad_mode=pad_mode, plan=plan, remat=remat)
    if function is not None:
        return wrap(function)
    return wrap


# ---------------------------------------------------------------------------
# recompute (gradient checkpointing)

def recompute(layer_or_fn, *args, policy=None, **kwargs):
    """Run a Layer/function with rematerialization (reference:
    RecomputeOptimizer / fleet recompute; TPU-native: jax.checkpoint).

    Usage: ``out = jit.recompute(block, x)`` — activations inside `block`
    are recomputed during backward, trading FLOPs for HBM.

    ``policy=`` names what the checkpoint may keep: ``"full"`` (default —
    save only the inputs), or ``"dots"`` (checkpoint_dots: matmul
    outputs stay, the elementwise tail recomputes).
    """
    from .dispatch import apply
    from .nn.layer import bind_state, _remat_suspended
    from . import autograd as _ag
    from .memory_plan import checkpoint_policy
    ckpt_policy = checkpoint_policy(policy)

    if isinstance(layer_or_fn, Layer):
        from .nn.moe import MoEFFN
        layer = layer_or_fn
        holder_map = dict(layer.named_parameters())
        for n, b in layer.named_buffers():
            if isinstance(b, Tensor):
                holder_map["buffer:" + n] = b
        names = sorted(holder_map)
        # None inputs (e.g. an absent attention mask) can't be traced —
        # record their positions and re-insert at call time
        arg_slots = [a is not None for a in args]
        live_args = tuple(a for a in args if a is not None)
        n_in = len(live_args)
        # MoE sublayers stash their aux (load-balance) loss on themselves
        # during forward — inside jax.checkpoint that Tensor would hold an
        # inner-trace tracer, so thread the aux values out as EXPLICIT
        # checkpoint outputs and re-stash them afterwards
        moe_subs = [l for l in layer.sublayers(include_self=True)
                    if isinstance(l, MoEFFN)]

        def impl(rng_key, *vals):
            # the RNG key is threaded EXPLICITLY: stochastic ops inside
            # (dropout) must not advance the global key with a tracer
            # from the checkpoint trace (leak), and the backward replay
            # must regenerate identical masks
            xs, param_vals = vals[:n_in], vals[n_in:]
            it = iter(xs)
            full = [Tensor(next(it)) if live else None
                    for live in arg_slots]
            state = dict(zip(names, param_vals))
            saved = prandom._global_key.data
            prandom._global_key.data = rng_key
            try:
                # suspend the layer remat hook: the subtree is already
                # inside THIS checkpoint (re-wrapping would nest
                # checkpoints — and recurse, since the hook calls back
                # into recompute). Set inside impl so the backward
                # replay is covered too.
                with _remat_suspended():
                    with bind_state(layer, state):
                        with _ag.no_grad():
                            out = layer(*full, **kwargs)
            finally:
                prandom._global_key.data = saved
            out = out.data if isinstance(out, Tensor) else out
            auxs = tuple(l.aux_loss.data for l in moe_subs)
            return (out,) + auxs if moe_subs else out

        ckpt = jax.checkpoint(impl, policy=ckpt_policy)
        tensors = (prandom.next_key_graph(),) + live_args + tuple(
            holder_map[n] for n in names)
        if not moe_subs:
            return apply(ckpt, tensors, name="recompute")
        res = apply(ckpt, tensors, name="recompute",
                    n_out=1 + len(moe_subs))
        for l, a in zip(moe_subs, res[1:]):
            l.aux_loss = a
        return res[0]

    fn = layer_or_fn
    # same None-slot contract as the Layer branch: record positions of
    # None args and re-insert them at trace time
    arg_slots = [a is not None for a in args]
    live_args = tuple(a for a in args if a is not None)

    def impl(rng_key, *xs):
        # same explicit RNG threading as the Layer branch (tracer-leak +
        # backward-replay-mask invariants)
        it = iter(xs)
        full = [Tensor(next(it)) if live else None for live in arg_slots]
        saved = prandom._global_key.data
        prandom._global_key.data = rng_key
        try:
            with _remat_suspended():
                with _ag.no_grad():
                    out = fn(*full, **kwargs)
        finally:
            prandom._global_key.data = saved
        return out.data if isinstance(out, Tensor) else out

    return apply(jax.checkpoint(impl, policy=ckpt_policy),
                 (prandom.next_key_graph(),) + live_args, name="recompute")


class TracedLayer:
    """reference: fluid.dygraph.TracedLayer — trace a layer for inference."""

    def __init__(self, layer, example_inputs):
        self._layer = layer
        self._static = to_static(lambda *xs: layer(*xs), models=[layer],
                                 optimizers=[])
        self._example = example_inputs

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        out = tl(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._static(*args)


def save(layer, path, input_spec=None):
    """paddle.jit.save parity — delegates to io.save_inference_model."""
    from . import io as pio
    pio.save_inference_model(path, layer, input_spec=input_spec)


def load(path):
    from . import io as pio
    return pio.load_inference_model(path)
