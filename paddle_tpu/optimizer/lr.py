"""paddle_tpu.optimizer.lr — learning-rate schedulers.

TPU-native rebuild of the reference's LR schedules
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py — noam,
exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay, cosine_decay, linear_lr_warmup — and the dygraph
LearningRateDecay classes in dygraph/learning_rate_scheduler.py).

Each scheduler computes the lr as a pure function of the step counter. The
owning Optimizer keeps the current value in a device scalar Tensor, so a
``jit.to_static`` train step treats the lr as carried input state (no
retrace when it changes) — the XLA analogue of the reference's lr var living
in the Program's scope.
"""
from __future__ import annotations

import math


class LRScheduler:
    """Base (reference: dygraph LearningRateDecay)."""

    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.verbose = verbose
        self._owner = None  # set by Optimizer
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self._owner is not None:
            self._owner._set_lr_value(self.last_lr)
        return self.last_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]
        self.last_lr = state["last_lr"]

    def __call__(self):
        return self.last_lr


class NoamDecay(LRScheduler):
    """reference: noam_decay — lr = d^-0.5 * min(n^-0.5, n * warmup^-1.5)"""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = max(self.last_epoch, 1)
        return (self.base_lr * self.d_model ** -0.5 *
                min(n ** -0.5, n * self.warmup_steps ** -1.5))


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle and step > 0:
            decay_steps = decay_steps * math.ceil(step / decay_steps)
        step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class CosineAnnealingDecay(LRScheduler):
    """reference: cosine_decay."""

    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class LinearWarmup(LRScheduler):
    """reference: linear_lr_warmup — wraps another scheduler or float."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = learning_rate.base_lr if isinstance(learning_rate,
                                                   LRScheduler) else learning_rate
        super().__init__(base, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.start_lr + (self.end_lr - self.start_lr) *
                    self.last_epoch / self.warmup_steps)
        if isinstance(self.lr, LRScheduler):
            self.lr.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr.get_lr()
        return self.lr


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class ReduceOnPlateau(LRScheduler):
    """reference: ReduceLROnPlateau (dygraph)."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._current = learning_rate
        super().__init__(learning_rate, -1, verbose)

    def get_lr(self):
        return self._current

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            self.last_lr = self._current
            if self._owner is not None:
                self._owner._set_lr_value(self.last_lr)
            return self.last_lr
        value = float(metrics)
        better = (self.best is None or
                  (value < self.best - self.threshold if self.mode == "min"
                   else value > self.best + self.threshold))
        if better:
            self.best = value
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self._current = max(self._current * self.factor, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
        self.last_lr = self._current
        if self._owner is not None:
            self._owner._set_lr_value(self.last_lr)
        return self.last_lr


# fluid functional aliases (reference: layers/learning_rate_scheduler.py)
def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    class _Exp(LRScheduler):
        def get_lr(self):
            p = self.last_epoch / decay_steps
            if staircase:
                p = math.floor(p)
            return learning_rate * decay_rate ** p
    return _Exp(learning_rate)


def piecewise_decay(boundaries, values):
    return PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return CosineAnnealingDecay(learning_rate,
                                T_max=step_each_epoch * epochs)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    return PolynomialDecay(learning_rate, decay_steps, end_learning_rate,
                           power, cycle)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)
