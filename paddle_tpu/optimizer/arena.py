"""paddle_tpu.optimizer.arena — the zero-copy flat parameter arena.

One contiguous 1-D buffer per dtype holds every trainable parameter,
with the optimizer's slot state (m/v moments, beta pows) mirrored as
equally-flat buffers in the same layout. Built ONCE at structure-version
time (the only concat the feature ever pays); afterwards the per-step
path is pure elementwise math over the flat buffers:

* the forward pass reads parameters through cached ``(offset, shape)``
  slice views of the flat buffer — XLA fuses a static slice into its
  consumer, so there is no per-step split traffic;
* gradients are packed with one ordered concat per dtype group under a
  dedicated ``arena.pack`` profile scope (the unavoidable cost of fresh
  per-leaf cotangents — NOT attributed to ``opt.*``);
* the update is one flat ``adam_step_flat`` call per group
  (ops/pallas/fused_adam.py) instead of the multi-tensor path's
  4-gather + 3-scatter rebuild every step;
* grad-sync buckets (parallel.overlap) are CONTIGUOUS SLICES of the
  same layout (``bucket_bounds``), so exact/quantized/overlap reduce
  operates in place on the training buffers.

Coherence contract: after a flat update the per-leaf ``p.data`` payloads
are STALE until :meth:`sync_leaves` runs. Staleness is resolved lazily
at the read boundaries — ``Tensor.numpy()``, ``Layer.state_dict()``,
``CheckpointManager.save``, and any ``jit.to_static`` function that does
not itself carry the arena — through the ``tensor._arena_hook`` global,
so a training loop never pays a per-step re-scatter. Writes to a covered
parameter (``Tensor.set_value``, e.g. a checkpoint restore) mark the
arena dirty and the flat buffer repacks eagerly before the next step.

Checkpoint compatibility is bidirectional by construction:
``per_leaf_state`` emits standard ``pname@slot`` entries sliced from the
flat buffers (an arena checkpoint is indistinguishable from a per-leaf
one) and ``load_leaf_state`` scatters per-leaf checkpoints back into the
flat layout.

Scope: the arena keeps EXACT per-leaf bit-identity only while every
member steps in lockstep (the jit/SPMD training reality). Members with
*no* grad in a step are masked out (param, moments, pows untouched per
element) — the shared per-group beta pows then follow the multi-tensor
kernel's semantics note in ops/pallas/fused_adam.py.
"""
from __future__ import annotations

import warnings
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .. import tensor as _ptensor
from .. import monitor as _monitor

__all__ = ["ParamArena", "flush", "sync_all"]

# pad each dtype group to a full (8, 128) f32 tile multiple so the
# Pallas flat kernel's (rows, 128) view is a free reshape, never a pad
ALIGN = 1024

_ALL = weakref.WeakSet()    # every live arena
_STALE = weakref.WeakSet()  # flat buffer newer than the leaf views
_DIRTY = weakref.WeakSet()  # leaf payloads newer than the flat buffer


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _hook(t, event):
    """Installed as ``paddle_tpu.tensor._arena_hook`` while arenas
    exist. ``read`` (Tensor.numpy) syncs stale leaves on demand;
    ``write`` (Tensor.set_value, pre-write) first pulls every leaf fresh
    so the incoming value is not clobbered by a later full sync, then
    marks the covering arena for repack."""
    if event == "read":
        for a in list(_STALE):
            if id(t) in a._pid_set:
                a.sync_leaves()
    elif event == "write":
        for a in list(_ALL):
            if id(t) in a._pid_set:
                if a in _STALE:
                    a.sync_leaves()
                _DIRTY.add(a)


def _install_hook():
    _ptensor._arena_hook = _hook


def _maybe_uninstall():
    if not _ALL:
        _ptensor._arena_hook = None


def flush(exclude=()):
    """Settle all pending coherence work: repack leaf-dirty arenas
    (restored checkpoints) and sync stale leaves, except arenas in
    ``exclude`` (a compiled step's own arenas — their flat buffer IS the
    carried state, leaf staleness is free there)."""
    for a in list(_DIRTY):
        a.repack_leaves()
    ex = {id(a) for a in exclude}
    for a in list(_STALE):
        if id(a) not in ex:
            a.sync_leaves()


def sync_all():
    """Checkpoint/read-boundary helper: make every leaf view concrete."""
    flush()


class _Group:
    """One dtype's contiguous region: entries are (param, offset, size,
    shape) in parameter-list order; ``total`` includes the tile pad."""
    __slots__ = ("dtype", "tag", "entries", "total", "flat", "slots",
                 "pows")

    def __init__(self, dtype, tag):
        self.dtype = dtype
        self.tag = tag
        self.entries = []
        self.total = 0
        self.flat = None
        self.slots = {}
        self.pows = {}


class ParamArena:
    def __init__(self, params, slot_names=(), pow_names=(), adopt=None):
        """``params``: ordered trainable parameters. ``slot_names``:
        flat per-element slot buffers to mirror (e.g. moment1/moment2).
        ``pow_names``: shared per-group scalar accumulators initialised
        to 1.0 (beta pows). ``adopt``: an optimizer ``_accumulators``
        dict whose existing per-leaf slot values seed the flat buffers
        (mid-training enable)."""
        self.slot_names = tuple(slot_names)
        self.pow_names = tuple(pow_names)
        # memory_plan bf16-master: when set, bind_views casts the
        # IN-TRACE leaf views to this dtype while the flat buffer (the
        # fp32 master) stays the carried state — eager reads and
        # checkpoints keep seeing exact fp32 leaves
        self.view_dtype = None
        self.groups = []
        self._by_pid = {}   # id(param) -> (group, entry index)
        self._pid_set = set()
        by_tag = {}
        for p in params:
            dt = jnp.dtype(p.data.dtype)
            grp = by_tag.get(dt.name)
            if grp is None:
                grp = _Group(dt, dt.name)
                by_tag[dt.name] = grp
                self.groups.append(grp)
            n = int(np.prod(p.data.shape)) if p.data.shape else 1
            self._by_pid[id(p)] = (grp, len(grp.entries))
            self._pid_set.add(id(p))
            grp.entries.append((p, grp.total, n, tuple(p.data.shape)))
            grp.total += n
        adopt = adopt or {}
        pow_seed, pow_src = {}, None
        for grp in self.groups:
            pad = (-grp.total) % ALIGN
            grp.total += pad
            parts = [jnp.ravel(p.data).astype(grp.dtype)
                     for p, _, _, _ in grp.entries]
            if pad:
                parts.append(jnp.zeros((pad,), grp.dtype))
            grp.flat = Tensor(jnp.concatenate(parts) if len(parts) > 1
                              else parts[0], name=f"arena.{grp.tag}.flat")
            for sname in self.slot_names:
                buf = jnp.zeros((grp.total,), grp.dtype)
                for p, off, n, _ in grp.entries:
                    seed = adopt.get(id(p), {}).get(sname)
                    if seed is not None:
                        buf = buf.at[off:off + n].set(
                            jnp.ravel(seed.data).astype(grp.dtype))
                grp.slots[sname] = Tensor(
                    buf, name=f"arena.{grp.tag}.{sname}")
            for pname in self.pow_names:
                val = 1.0
                for p, _, _, _ in grp.entries:
                    seed = adopt.get(id(p), {}).get(pname)
                    if seed is not None:
                        val = float(jax.device_get(seed.data))
                        # keyed per (group, pow): each group carries its
                        # own pow scalar, and dtype rounding makes pows
                        # differ ACROSS groups even in lockstep
                        pow_seed.setdefault((grp.tag, pname),
                                            set()).add(val)
                        if pow_src is None:
                            pow_src = p
                grp.pows[pname] = Tensor(
                    jnp.asarray(val, grp.dtype),
                    name=f"arena.{grp.tag}.{pname}")
        if any(len(v) > 1 for v in pow_seed.values()):
            warnings.warn(
                "flat arena: adopted per-leaf beta-pow slots are not all "
                "equal (params stepped out of lockstep); the arena "
                "carries ONE shared pow per group — bias correction now "
                "follows the multi-tensor semantics", RuntimeWarning)
        self._pow_restore_seen = {}
        _ALL.add(self)
        _install_hook()
        if _monitor.enabled():
            _monitor.counter("optimizer.arena_build").inc()

    # -- identity ------------------------------------------------------------
    @property
    def param_ids(self):
        return self._pid_set

    def signature(self):
        return tuple((id(p), grp.tag, n)
                     for grp in self.groups
                     for p, _, n, _ in grp.entries)

    def matches(self, params):
        """True when ``params`` (ordered trainables) are exactly the
        members this arena was built over, same dtypes and sizes.
        Inside a traced step ``bind_views`` may have rebound the leaves
        to ``view_dtype`` casts of the fp32 master — that is this
        arena's own doing, not a membership change, so the view dtype
        counts as a match."""
        view = (jnp.dtype(self.view_dtype).name
                if self.view_dtype is not None else None)
        sig = self.signature()
        if len(params) != len(sig):
            return False
        for p, (si, sd, sn) in zip(params, sig):
            n = int(np.prod(p.data.shape)) if p.data.shape else 1
            dt = jnp.dtype(p.data.dtype).name
            if id(p) != si or n != sn or (dt != sd and dt != view):
                return False
        return True

    def holders(self):
        """name → Tensor map of every flat buffer, registered as one
        ``_accumulators`` entry so jit.to_static / the Executor carry
        them as donated state under stable names."""
        out = {}
        for grp in self.groups:
            out[f"{grp.tag}.flat"] = grp.flat
            for sname, t in grp.slots.items():
                out[f"{grp.tag}.{sname}"] = t
            for pname, t in grp.pows.items():
                out[f"{grp.tag}.{pname}"] = t
        return out

    def dissolve(self):
        _ALL.discard(self)
        _STALE.discard(self)
        _DIRTY.discard(self)
        _maybe_uninstall()

    # -- leaf view coherence -------------------------------------------------
    def bind_views(self, resave=True):
        """Point every member's ``.data`` at its slice of the (possibly
        traced) flat buffer. Returns the saved payloads for
        :meth:`unbind_views` when ``resave``; the mid-trace rebind after
        an update passes ``resave=False``."""
        saved = {} if resave else None
        for grp in self.groups:
            flat = grp.flat.data
            cast = (self.view_dtype is not None and _is_tracer(flat)
                    and jnp.dtype(self.view_dtype) != grp.dtype)
            for p, off, n, shape in grp.entries:
                if resave:
                    saved[id(p)] = (p, p.data)
                v = flat[off:off + n].reshape(shape)
                if cast:
                    # bf16 device-resident views over the fp32 master:
                    # the forward reads half-width params, grads cast
                    # back to fp32 in pack_grads, the update applies to
                    # the master. Trace-only on purpose — eager views
                    # stay exact fp32.
                    v = v.astype(self.view_dtype)
                p.data = v
        return saved

    def unbind_views(self, saved):
        for p, data in saved.values():
            p.data = data

    def sync_leaves(self):
        """Materialise every leaf view from the flat buffer (the lazy
        re-scatter paid only at read boundaries, never per step)."""
        if any(_is_tracer(grp.flat.data) for grp in self.groups):
            self.bind_views(resave=False)
            return
        for grp in self.groups:
            flat = grp.flat.data
            for p, off, n, shape in grp.entries:
                p.data = flat[off:off + n].reshape(shape)
        _STALE.discard(self)
        if _monitor.enabled():
            _monitor.counter("optimizer.arena_leaf_sync").inc()

    def mark_stale(self):
        _STALE.add(self)

    def repack_leaves(self):
        """Rebuild the flat buffers from the leaf payloads (a restored
        checkpoint or manual ``set_value`` wrote fresh leaves)."""
        for grp in self.groups:
            if _is_tracer(grp.flat.data):
                continue
            pad = grp.total - sum(n for _, _, n, _ in grp.entries)
            parts = [jnp.ravel(p.data).astype(grp.dtype)
                     for p, _, _, _ in grp.entries]
            if pad:
                parts.append(jnp.zeros((pad,), grp.dtype))
            grp.flat.data = (jnp.concatenate(parts) if len(parts) > 1
                             else parts[0])
        _DIRTY.discard(self)
        _STALE.discard(self)
        if _monitor.enabled():
            _monitor.counter("optimizer.arena_repack").inc()

    @property
    def needs_repack(self):
        return self in _DIRTY

    def finish_step(self):
        """Post-update coherence: inside a trace, rebind the leaf views
        onto the NEW flat tracers (later in-trace reads stay
        consistent); eagerly, refresh the leaves now — eager mode has no
        write-back boundary to defer to."""
        self._pow_restore_seen.clear()
        if any(_is_tracer(grp.flat.data) for grp in self.groups):
            self.bind_views(resave=False)
        else:
            self.sync_leaves()

    # -- grad packing --------------------------------------------------------
    def pack_grads(self, params_grads):
        """One ordered concat per dtype group over the step's per-leaf
        gradients (post clip/regularizer), under the ``arena.pack``
        scope so the cost ledger attributes the pack OUTSIDE ``opt.*``.
        Members without a grad this step contribute a zero segment and a
        0 mask entry (their param/moments stay untouched per element).
        Returns ``[(group, flat_grad, mask_or_None), ...]`` for live
        groups, or None when no member has a grad."""
        by_pid = {id(p): g for p, g in params_grads if g is not None}
        if not by_pid:
            return None
        _monitor.profile.register_scope("arena.pack", "op")
        packed = []
        with jax.named_scope("arena.pack"):
            for grp in self.groups:
                segs, flags, any_live = [], [], False
                for p, off, n, shape in grp.entries:
                    g = by_pid.get(id(p))
                    if g is None:
                        segs.append(jnp.zeros((n,), grp.dtype))
                        flags.append(False)
                    else:
                        segs.append(jnp.ravel(g).astype(grp.dtype))
                        flags.append(True)
                        any_live = True
                if not any_live:
                    continue
                pad = grp.total - sum(n for _, _, n, _ in grp.entries)
                if pad:
                    segs.append(jnp.zeros((pad,), grp.dtype))
                flat_g = (jnp.concatenate(segs) if len(segs) > 1
                          else segs[0])
                mask = None
                if not all(flags):
                    # host-side constant: 1 where the member stepped
                    m = np.zeros((grp.total,), bool)
                    for (p, off, n, _), live in zip(grp.entries, flags):
                        if live:
                            m[off:off + n] = True
                    mask = jnp.asarray(m)
                packed.append((grp, flat_g, mask))
        return packed or None

    # -- grad-sync layout ----------------------------------------------------
    def bucket_bounds(self, bucket_bytes=None, plan=None):
        """Contiguous-slice bucket plan per group for parallel.overlap:
        ``{tag: [(start, stop), ...]}`` tiles ``[0, total)`` (pad rides
        in the last bucket), each bucket one in-place slice of the flat
        gradient layout — the arena replaces plan_buckets' per-leaf
        gather with pure offsets.

        ``plan`` (a parallel.planner.MeshPlan) asserts the layout
        contract: the arena packs every member into ONE replicated
        buffer per dtype, so a plan that shards any member param would
        make these bounds non-contiguous per shard. Such a plan raises
        here instead of silently producing torn buckets — use the
        per-leaf path (arena.flat_fallback accounting) for
        tensor-sharded layouts."""
        from ..parallel.overlap import DEFAULT_BUCKET_BYTES, plan_buckets
        if plan is not None:
            named = {}
            for grp in self.groups:
                for i, (p, _off, _n, shape) in enumerate(grp.entries):
                    named[getattr(p, "name", None)
                          or f"{grp.tag}.param{i}"] = tuple(shape)
            bad = plan.arena_compatible(named)
            if bad is not None:
                raise ValueError(
                    f"mesh_plan shards arena member {bad[0]!r} as "
                    f"{bad[1]} — the flat arena requires replicated "
                    f"params; drop flat_arena or replicate the param "
                    f"in the plan")
        if bucket_bytes is None:
            bucket_bytes = DEFAULT_BUCKET_BYTES
        out = {}
        for grp in self.groups:
            sizes = [n for _, _, n, _ in grp.entries]
            idx_buckets = plan_buckets(sizes, bucket_bytes,
                                       itemsize=grp.dtype.itemsize)
            bounds = []
            for idxs in idx_buckets:
                start = grp.entries[idxs[0]][1]
                last = grp.entries[idxs[-1]]
                bounds.append((start, last[1] + last[2]))
            if bounds:
                bounds[-1] = (bounds[-1][0], grp.total)
            else:
                bounds = [(0, grp.total)]
            out[grp.tag] = bounds
        return out

    # -- checkpoint interop --------------------------------------------------
    def per_leaf_state(self, named_params):
        """Standard per-leaf ``pname@slot`` entries sliced out of the
        flat buffers — an arena checkpoint round-trips through a
        per-leaf optimizer (and vice versa) with no format marker."""
        out = {}
        for pname, p in named_params:
            hit = self._by_pid.get(id(p))
            if hit is None:
                continue
            grp, i = hit
            _, off, n, shape = grp.entries[i]
            for sname, t in grp.slots.items():
                out[f"{pname}@{sname}"] = Tensor(
                    t.data[off:off + n].reshape(shape),
                    name=f"{pname}_{sname}")
            for pow_name, t in grp.pows.items():
                # copy: a bare alias would die when the next donated
                # step consumes the pow holder's buffer
                out[f"{pname}@{pow_name}"] = Tensor(
                    jnp.array(t.data, copy=True), name=f"{pname}_{pow_name}")
        return out

    _warned_pow_restore = False

    def load_leaf_state(self, p, slot_values):
        """Scatter one param's per-leaf checkpoint slots into the flat
        layout. Beta pows restore into the shared per-group scalar; a
        non-lockstep checkpoint warns once (multi-tensor semantics)."""
        grp, i = self._by_pid[id(p)]
        _, off, n, shape = grp.entries[i]
        for sname, value in slot_values.items():
            arr = jnp.asarray(value)
            if sname in grp.slots:
                t = grp.slots[sname]
                t.data = t.data.at[off:off + n].set(
                    jnp.ravel(arr).astype(grp.dtype))
            elif sname in grp.pows:
                t = grp.pows[sname]
                new = float(jax.device_get(arr))
                # non-lockstep detection: compare against what OTHER
                # params restored into this shared scalar since the last
                # step (not against the live value — a plain resume
                # legitimately rewinds it)
                seen = self._pow_restore_seen.setdefault(
                    (grp.tag, sname), new)
                if seen != new and not ParamArena._warned_pow_restore:
                    warnings.warn(
                        "flat arena restore: per-leaf beta-pow values "
                        "differ across params; the shared per-group pow "
                        "keeps the last one (multi-tensor semantics)",
                        RuntimeWarning)
                    ParamArena._warned_pow_restore = True
                self._pow_restore_seen[(grp.tag, sname)] = new
                t.data = jnp.asarray(new, grp.dtype)

    def leaf_slot_tensors(self, p):
        """Fresh per-leaf slot Tensors for one member (used when the
        arena is dissolved back to per-leaf mode)."""
        grp, i = self._by_pid[id(p)]
        _, off, n, shape = grp.entries[i]
        out = {}
        for sname, t in grp.slots.items():
            out[sname] = Tensor(t.data[off:off + n].reshape(shape),
                                name=f"{getattr(p, 'name', 'p')}_{sname}")
        for pow_name, t in grp.pows.items():
            out[pow_name] = Tensor(jnp.array(t.data, copy=True),
                                   name=f"{getattr(p, 'name', 'p')}"
                                        f"_{pow_name}")
        return out


# ---------------------------------------------------------------------------
# static-Executor functional path


def static_apply(opt, params_grads, param_vals, slot_vals, lr):
    """Arena update for the static Executor's functional ``run_fn``:
    params stay per-leaf (the Program's carried-state contract) but the
    m/v/pow slots live FLAT, so the per-step repack drops from the
    multi-tensor path's 4 gathers + 3 scatters to 2 gathers (p, g) + 1
    split (new p) — the slot buffers never leave the arena layout.

    ``params_grads``: the Executor's (param, grad) pairs after clip/reg;
    ``param_vals``: {id(param): current traced value};
    ``slot_vals``: {arena holder name: traced value}.
    Returns (new_param_by_pid, new_slot_vals)."""
    from ..ops.pallas.fused_adam import adam_step_flat
    arena = opt._arena
    new_params, new_slots = {}, dict(slot_vals)
    by_pid = {id(p): g for p, g in params_grads if g is not None}
    wd = getattr(opt, "_wd", 0.0)
    for grp in arena.groups:
        segs, pparts, flags, any_live = [], [], [], False
        for p, off, n, shape in grp.entries:
            g = by_pid.get(id(p))
            pval = param_vals.get(id(p), p.data)
            pparts.append(jnp.ravel(pval).astype(grp.dtype))
            if g is None:
                segs.append(jnp.zeros((n,), grp.dtype))
                flags.append(False)
            else:
                segs.append(jnp.ravel(g).astype(grp.dtype))
                flags.append(True)
                any_live = True
        if not any_live:
            continue
        pad = grp.total - sum(n for _, _, n, _ in grp.entries)
        if pad:
            segs.append(jnp.zeros((pad,), grp.dtype))
            pparts.append(jnp.zeros((pad,), grp.dtype))
        flat_g = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
        flat_p = jnp.concatenate(pparts) if len(pparts) > 1 else pparts[0]
        mask = None
        if not all(flags):
            m = np.zeros((grp.total,), bool)
            for (p, off, n, _), f in zip(grp.entries, flags):
                if f:
                    m[off:off + n] = True
            mask = jnp.asarray(m)
        b1p = slot_vals[f"{grp.tag}.beta1_pow"] * opt._beta1
        b2p = slot_vals[f"{grp.tag}.beta2_pow"] * opt._beta2
        new_p, new_m, new_v = adam_step_flat(
            flat_p, flat_g,
            slot_vals[f"{grp.tag}.moment1"],
            slot_vals[f"{grp.tag}.moment2"],
            lr, b1p, b2p, beta1=opt._beta1, beta2=opt._beta2,
            eps=opt._eps, weight_decay=wd, mask=mask,
            use_fused=opt._use_fused)
        new_slots[f"{grp.tag}.moment1"] = new_m
        new_slots[f"{grp.tag}.moment2"] = new_v
        new_slots[f"{grp.tag}.beta1_pow"] = b1p
        new_slots[f"{grp.tag}.beta2_pow"] = b2p
        for (p, off, n, shape), f in zip(grp.entries, flags):
            if f:
                new_params[id(p)] = new_p[off:off + n].reshape(shape)
    return new_params, new_slots
