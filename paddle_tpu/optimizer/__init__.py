"""paddle_tpu.optimizer — the optimizer suite.

TPU-native rebuild of the reference's optimizers
(reference: python/paddle/fluid/optimizer.py — SGD, Momentum, LarsMomentum,
Adagrad, DecayedAdagrad, Adadelta, Adam, Adamax, Lamb, RMSProp, Ftrl,
Dpsgd, ModelAverage, ExponentialMovingAverage, LookaheadOptimizer,
RecomputeOptimizer, PipelineOptimizer; and the C++ adam_op/momentum_op
kernels).

Design: each optimizer implements one pure `_rule(param, grad, slots, lr)`
over jnp arrays. In dygraph the rule runs eagerly per parameter; under
``jit.to_static`` the whole loop is traced into the train step, so XLA fuses
all parameter updates with the backward pass (the reference needs a fused
multi-tensor adam CUDA kernel for this; XLA fusion + optional Pallas fused
adam in ops/pallas give it for free). Slot state lives in Tensors, so it is
carried state for to_static and checkpointable.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, Parameter
from ..regularizer import WeightDecayRegularizer, L2Decay
from ..clip import ClipGradBase
from .. import monitor as _monitor
from ..resilience import guard as _rguard
from . import lr as lr_sched
from .lr import LRScheduler


class Optimizer:
    """Base optimizer (reference: optimizer.py:Optimizer)."""

    # flat-arena capability: subclasses that support the zero-copy flat
    # parameter arena (optimizer.arena) name their per-element slot
    # buffers here; None = unsupported (flat_arena=True raises)
    _arena_slots = None
    _arena_pows = ()

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 regularization=None, grad_sync=None, flat_arena=False):
        if parameters is not None and not isinstance(parameters,
                                                     (list, tuple)):
            parameters = list(parameters)
        self._parameter_list = list(parameters) if parameters else None
        self._arena = None
        self._flat_arena = False
        # memory_plan hooks: host offload of the arena's slot buffers
        # (memory_plan.attach_offload) and the bf16-view dtype the
        # arena binds inside traces (fp32 master weights)
        self._offloader = None
        self._arena_view_dtype = None
        if flat_arena:
            self.set_flat_arena(True)
        # gradient-sync scheduler (parallel.overlap): a mode string
        # ("exact"|"quantized"|"overlap") or a GradSyncScheduler. Under
        # GSPMD the grads reaching step() are already reduced, so at
        # this level the scheduler contributes lag-1 apply pipelining +
        # comm.* accounting; wire-level bucketed/quantized reduces live
        # in explicit-DDP loops (scheduler.reduce) and megatron.
        self._grad_sync = None
        if grad_sync is not None:
            self.set_grad_sync(grad_sync)
        self._grad_clip = grad_clip
        # weight_decay may be a float (L2) or a regularizer object
        wd = weight_decay if weight_decay is not None else regularization
        if isinstance(wd, (int, float)):
            wd = L2Decay(float(wd))
        self._regularization = wd
        self._lr_scheduler = None
        self._lr_decay = None
        from ..fluid.dygraph_lr import LearningRateDecay
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            learning_rate._owner = self
            lr_value = learning_rate.last_lr
        elif isinstance(learning_rate, LearningRateDecay):
            # 1.x dygraph decay protocol: the OPTIMIZER calls the decay
            # each step (reference optimizer.py dygraph minimize path),
            # vs LRScheduler's user-driven scheduler.step().
            self._lr_decay = learning_rate
            # current-step value WITHOUT advancing, so get_lr() is right
            # before training. peek() — NOT step(): LinearLrWarmup's
            # step() calls a wrapped inner decay, which would advance
            # the inner schedule once before training ever starts.
            lr_value = float(learning_rate.peek())
        else:
            lr_value = float(learning_rate)
        # lr lives on device so compiled steps treat it as input state
        self._lr_tensor = Tensor(jnp.asarray(lr_value, jnp.float32),
                                 name="learning_rate")
        self._accumulators = {}  # id(param) -> {slot_name: Tensor}
        self._aux_state = {}     # scalar aux state (step counters etc.)

    # -- lr management ------------------------------------------------------
    def _set_lr_value(self, value):
        self._lr_tensor.data = jnp.asarray(value, jnp.float32)

    def set_lr(self, value):
        self._set_lr_value(value)

    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler.last_lr
        return float(jax.device_get(self._lr_tensor.data))

    @property
    def _learning_rate(self):
        return self._lr_tensor.data

    # -- slots --------------------------------------------------------------
    def _slot(self, param, name, init=None, shape=None, dtype=None):
        pid = id(param)
        slots = self._accumulators.setdefault(pid, {})
        if name not in slots:
            shape = shape if shape is not None else param.data.shape
            dtype = dtype or param.data.dtype
            value = jnp.zeros(shape, dtype) if init is None else jnp.full(
                shape, init, dtype)
            slots[name] = Tensor(value, name=f"{param.name}_{name}")
        return slots[name]

    # -- the per-parameter update rule (override) ---------------------------
    def _rule(self, p, g, slots, lr):
        raise NotImplementedError

    def _params(self):
        if self._parameter_list is None:
            raise ValueError(
                "optimizer constructed without `parameters`; pass "
                "parameters=model.parameters() (reference dygraph requires "
                "parameter_list too)")
        return self._parameter_list

    # -- apply --------------------------------------------------------------
    def step(self):
        """Apply one update from accumulated .grad (reference: dygraph
        minimize path in optimizer.py:Optimizer.apply_gradients)."""
        if _monitor.enabled():
            _monitor.counter(f"optimizer.step.{type(self).__name__}").inc()
        with _monitor.trace.span("optimizer.step",
                                 cls=type(self).__name__):
            self._step_body()

    def set_grad_sync(self, grad_sync):
        """Attach a gradient-sync scheduler (a mode string builds one
        over the registered mesh). See parallel.overlap."""
        from ..parallel.overlap import GradSyncScheduler
        if isinstance(grad_sync, str):
            if grad_sync == "exact":
                self._grad_sync = None
                return self
            grad_sync = GradSyncScheduler(mode=grad_sync)
        self._grad_sync = grad_sync
        return self

    # -- flat parameter arena ------------------------------------------------
    def set_flat_arena(self, enable=True):
        """Toggle the zero-copy flat parameter arena (optimizer.arena):
        one contiguous buffer per dtype holds every trainable param and
        its mirrored slot state, so the per-step path has no
        concat/split HBM traffic. Adam/AdamW only. Disabling dissolves
        the arena back into ordinary per-leaf slots (values preserved),
        so the knob can flip mid-training."""
        enable = bool(enable)
        if enable:
            if self._arena_slots is None:
                raise ValueError(
                    f"flat_arena is not supported by "
                    f"{type(self).__name__}; use Adam or AdamW")
            self._flat_arena = True
            # the arena itself builds lazily (_ensure_arena) once every
            # parameter has concrete data
        else:
            if self._arena is not None:
                a = self._arena
                a.sync_leaves()
                self._accumulators.pop(id(a), None)
                for p in self._params():
                    if id(p) in a.param_ids:
                        self._accumulators[id(p)] = a.leaf_slot_tensors(p)
                a.dissolve()
                self._arena = None
            self._flat_arena = False
        return self

    def _ensure_arena(self):
        """Build (or rebuild after a structure change) the flat arena
        over the current trainables, adopting any existing per-leaf slot
        values; registers the flat buffers as ONE accumulators entry so
        jit/Executor carry them as donated state."""
        from .arena import ParamArena
        trainables = [p for p in self._params() if not p.stop_gradient]
        if self._arena is not None:
            if self._arena.matches(trainables):
                if self._arena.needs_repack:
                    self._arena.repack_leaves()
                self._arena.view_dtype = self._arena_view_dtype
                return self._arena
            # membership/dtype changed: dissolve into per-leaf slots
            # first so the new arena adopts the live values
            self.set_flat_arena(False)
            self._flat_arena = True
        arena = ParamArena(trainables, slot_names=self._arena_slots,
                           pow_names=self._arena_pows,
                           adopt=self._accumulators)
        for p in trainables:
            self._accumulators.pop(id(p), None)
        self._accumulators[id(arena)] = arena.holders()
        arena.view_dtype = self._arena_view_dtype
        self._arena = arena
        return arena

    def _arena_apply(self, arena, packed, lr):
        """Apply the flat update for every packed dtype group (subclass
        hook — only arena-capable classes are reachable here)."""
        raise NotImplementedError

    def _step_body(self):
        if self._lr_decay is not None:
            # host-side schedule: advance + refresh the device lr tensor
            # (under jit the tensor is input state, so no retrace)
            self._set_lr_value(self._lr_decay())
        params_grads = [(p, p._grad) for p in self._params()
                        if not (p.stop_gradient or p._grad is None)]
        if self._grad_sync is not None:
            params_grads = self._grad_sync.process(params_grads)
            if params_grads is None:
                return  # lag-1 warm-up: this step's grads are in flight
        # reference order (optimizer.py:apply_gradients): clip raw grads
        # first, then append the regularization term. Per-param clips
        # (set_gradient_clip param_list) go first, then the optimizer's
        # own clip or the fluid-global strategy.
        per_param = []
        for p, g in params_grads:
            pc = getattr(p, "grad_clip", None)
            if pc is not None and g is not None:
                g = pc([(p, g)])[0][1]
            per_param.append((p, g))
        params_grads = per_param
        grad_clip = self._grad_clip
        if grad_clip is None:
            from ..clip import get_gradient_clip
            grad_clip = get_gradient_clip()
        if grad_clip is not None:
            params_grads = grad_clip(params_grads)
        regularized = []
        for p, g in params_grads:
            if g is None:
                regularized.append((p, g))
                continue
            reg = p.regularizer or self._regularization
            if isinstance(reg, WeightDecayRegularizer):
                g = g + reg.grad_term(p.data)
            regularized.append((p, g))
        params_grads = regularized
        lr = self._lr_tensor.data
        g = _rguard.active()
        if g is not None:
            # resilience NaN guard: snapshot / apply / where-select (the
            # AMP scaler scheme — jit-safe, so a to_static-fused train
            # step skips poisoned updates inside the compiled computation)
            finite = _rguard.guarded_apply(
                self, params_grads,
                lambda: self._apply_update(params_grads, lr))
            g.note_device_flag(finite, optimizer=self)
            return
        self._apply_update(params_grads, lr)

    def _apply_update(self, params_grads, lr):
        """The raw update: batched multi-tensor path or the per-param
        _rule loop (split from step() so the resilience guard can
        bracket it with its snapshot/select machinery). Under an armed
        profiler the whole body runs inside a stable ``opt.<Cls>``
        named_scope, so monitor.profile can attribute the update math —
        one flag check when profiling is off."""
        if self._flat_arena and self._arena_slots is not None:
            arena = self._ensure_arena()
            # offload is an EAGER-path mechanism (the split step runs
            # the apply outside jit); inside a trace the transfers would
            # clobber tracers, so the hooks are gated on a clean trace
            offload = (self._offloader is not None
                       and jax.core.trace_state_clean())
            if offload:
                # wait for the H2D prefetch and rebind the moments
                # before the fused apply reads them
                self._offloader.collect(arena)
            # the grad pack (one ordered concat per dtype group) happens
            # OUTSIDE the opt.* scope — it is attributed to arena.pack,
            # and the opt.* region itself stays pure elementwise math
            packed = arena.pack_grads(params_grads)
            if packed is None:
                self._post_step()
                return
            if _monitor.profile.scopes_on:
                with jax.named_scope(
                        _monitor.profile.optimizer_scope(self)):
                    self._arena_apply(arena, packed, lr)
            else:
                self._arena_apply(arena, packed, lr)
            arena.finish_step()
            if offload:
                # page the just-updated moments out + start the next
                # prefetch; both overlap the next step's fwd/bwd
                self._offloader.page_out(arena)
            self._post_step()
            return
        if _monitor.profile.scopes_on:
            with jax.named_scope(_monitor.profile.optimizer_scope(self)):
                return self._apply_update_body(params_grads, lr)
        return self._apply_update_body(params_grads, lr)

    def _apply_update_body(self, params_grads, lr):
        if self._batched_update(params_grads, lr):
            self._post_step()
            return
        for p, g in params_grads:
            if g is None:
                continue
            self._pre_param(p)
            slots = self._accumulators.get(id(p), {})
            new_p, new_slots = self._rule(
                p.data, g, {n: t.data for n, t in slots.items()}, lr)
            p.data = new_p
            for n, v in new_slots.items():
                self._slot(p, n).data = v
        self._post_step()

    def _batched_update(self, params_grads, lr):
        """Hook: apply ALL updates in one dispatch (multi-tensor
        kernels). Return True if handled; False falls through to the
        per-param _rule loop. Base: no batched path."""
        return False

    def _ensure_all_slots(self):
        """Create every accumulator eagerly (used by jit.to_static so slot
        Tensors exist before tracing rather than materializing as tracers).
        In flat-arena mode the arena's flat buffers ARE the accumulators —
        no per-leaf slots exist."""
        if self._flat_arena and self._arena_slots is not None:
            self._ensure_arena()
            return
        for p in self._params():
            if not p.stop_gradient:
                self._pre_param(p)

    def _pre_param(self, p):
        # ensure slots exist before _rule reads them
        pass

    def _post_step(self):
        pass

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """reference dygraph semantics: grads already accumulated by
        loss.backward(); minimize applies them. In static mode the Program
        records this optimizer instead (see paddle_tpu.static)."""
        from ..dispatch import in_static_mode
        if in_static_mode():
            if self._lr_decay is not None:
                # the static Executor never calls step(), so the decay
                # would silently pin lr at its first value — the
                # reference raises for this lr type in static graphs too
                raise TypeError(
                    "1.x dygraph LearningRateDecay objects are "
                    "dygraph-only; in static mode use the functional "
                    "decays (fluid.layers.exponential_decay, ...) or an "
                    "optimizer.lr.LRScheduler")
            from ..static import record_optimizer
            return record_optimizer(self, loss)
        if loss is not None and loss._tape_node is not None and all(
                p._grad is None for p in self._params()
                if not p.stop_gradient):
            loss.backward()
        self.step()
        return None, None

    def clear_grad(self):
        for p in self._params():
            p.clear_gradient()

    clear_gradients = clear_grad

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        out = {"lr": self.get_lr()}
        names = {}
        named = [(p.name or f"param_{i}", p)
                 for i, p in enumerate(self._params())]
        if self._arena is not None:
            # emit standard per-leaf pname@slot views sliced from the
            # flat buffers — an arena checkpoint restores into a
            # per-leaf optimizer unchanged (and vice versa). Offloaded
            # moments come back device-resident first: the per-leaf
            # slicing needs settled arrays, and checkpoint exactness
            # requires the in-flight round trip to have landed.
            if self._offloader is not None:
                self._offloader.materialize(self._arena)
            self._arena.sync_leaves()
            out.update(self._arena.per_leaf_state(named))
        for pname, p in named:
            for sname, t in self._accumulators.get(id(p), {}).items():
                out[f"{pname}@{sname}"] = t
            names[pname] = p
        out["__aux__"] = dict(self._aux_state)
        if self._lr_scheduler is not None:
            out["__lr_sched__"] = self._lr_scheduler.state_dict()
        if self._lr_decay is not None:
            out["__lr_decay__"] = {"step_num": self._lr_decay.step_num}
        return out

    def set_state_dict(self, state):
        if self._flat_arena and self._arena_slots is not None:
            # build (or repack) the arena first so per-leaf checkpoint
            # slots scatter straight into the flat layout
            self._ensure_arena()
            if self._offloader is not None:
                self._offloader.materialize(self._arena)
        for i, p in enumerate(self._params()):
            pname = p.name or f"param_{i}"
            if self._arena is not None and id(p) in self._arena.param_ids:
                vals = {k.split("@", 1)[1]:
                        (v.data if isinstance(v, Tensor) else v)
                        for k, v in state.items()
                        if k.startswith(pname + "@")}
                if vals:
                    self._arena.load_leaf_state(p, vals)
                continue
            if not p.stop_gradient:
                self._pre_param(p)  # scalar slots (beta pows) get real shapes
            for key, value in state.items():
                if key.startswith(pname + "@"):
                    sname = key.split("@", 1)[1]
                    data = value.data if isinstance(value, Tensor) else value
                    slots = self._accumulators.setdefault(id(p), {})
                    if sname in slots:
                        slots[sname].set_value(data)
                    else:  # unknown slot: adopt the checkpoint's shape/dtype
                        arr = jnp.asarray(data)
                        self._slot(p, sname, shape=arr.shape,
                                   dtype=arr.dtype).set_value(arr)
        if "__aux__" in state:
            self._aux_state.update(state["__aux__"])
        if "__lr_sched__" in state and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state["__lr_sched__"])
        if "__lr_decay__" in state and self._lr_decay is not None:
            self._lr_decay.step_num = state["__lr_decay__"]["step_num"]


# ---------------------------------------------------------------------------
# concrete rules

class SGD(Optimizer):
    """reference: optimizer.py:SGDOptimizer / sgd_op.cc"""

    def _rule(self, p, g, slots, lr):
        return p - lr * g, {}


class Momentum(Optimizer):
    """reference: MomentumOptimizer / momentum_op.cc"""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _pre_param(self, p):
        self._slot(p, "velocity")

    def _rule(self, p, g, slots, lr):
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class DGCMomentum(Momentum):
    """reference: DGCMomentumOptimizer (deep gradient compression over
    NCCL rings). On TPU the all-reduce rides ICI inside the compiled step
    — sparsifying it would force gather/scatter HBM traffic that costs
    more than it saves — so this keeps DGC's momentum-correction update
    (momentum on the (virtually) compressed gradient, which for the
    identity sparsity equals plain momentum) and accepts the DGC
    signature for porting parity."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), parameters=None,
                 use_nesterov=False, num_trainers=None, **kw):
        super().__init__(learning_rate, momentum, parameters,
                         use_nesterov, **kw)
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = list(sparsity)


DGCMomentumOptimizer = DGCMomentum


class LarsMomentum(Optimizer):
    """reference: LarsMomentumOptimizer / lars_momentum_op.cc — layer-wise
    adaptive rate scaling (large-batch training)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _pre_param(self, p):
        self._slot(p, "velocity")

    def _rule(self, p, g, slots, lr):
        pn = jnp.sqrt(jnp.sum(jnp.square(p)))
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (pn > 0) & (gn > 0),
            lr * self._lars_coeff * pn / (gn + self._lars_wd * pn + 1e-12),
            lr)
        v = self._momentum * slots["velocity"] + local_lr * (
            g + self._lars_wd * p)
        return p - v, {"velocity": v}


class Adagrad(Optimizer):
    """reference: AdagradOptimizer / adagrad_op.cc"""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _pre_param(self, p):
        self._slot(p, "moment", init=self._init_acc)

    def _rule(self, p, g, slots, lr):
        m = slots["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._eps), {"moment": m}


class DecayedAdagrad(Optimizer):
    """reference: DecayedAdagradOptimizer / decayed_adagrad_op.cc"""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._decay = decay
        self._eps = epsilon

    def _pre_param(self, p):
        self._slot(p, "moment")

    def _rule(self, p, g, slots, lr):
        m = self._decay * slots["moment"] + (1 - self._decay) * g * g
        return p - lr * g / (jnp.sqrt(m) + self._eps), {"moment": m}


class Adadelta(Optimizer):
    """reference: AdadeltaOptimizer / adadelta_op.cc"""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._eps = epsilon
        self._rho = rho

    def _pre_param(self, p):
        self._slot(p, "avg_squared_grad")
        self._slot(p, "avg_squared_update")

    def _rule(self, p, g, slots, lr):
        rho, eps = self._rho, self._eps
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt((slots["avg_squared_update"] + eps) /
                           (asg + eps)) * g
        asu = rho * slots["avg_squared_update"] + (1 - rho) * update * update
        return p + lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adam(Optimizer):
    """reference: AdamOptimizer / adam_op.cc (incl. beta-pow accumulators).
    use_fused=True routes the update through the Pallas fused-adam kernel
    (reference: the fused multi-tensor adam CUDA path)."""

    _arena_slots = ("moment1", "moment2")
    _arena_pows = ("beta1_pow", "beta2_pow")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, lazy_mode=False,
                 use_fused=None, use_multi_tensor=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        # None = auto, resolved via pallas.enabled() when the step traces
        # (configure() before the first jitted step; traced steps keep
        # the choice they were compiled with)
        self._use_fused = use_fused
        self._use_multi_tensor = use_multi_tensor

    def _pre_param(self, p):
        self._slot(p, "moment1")
        self._slot(p, "moment2")
        self._slot(p, "beta1_pow", init=1.0, shape=())
        self._slot(p, "beta2_pow", init=1.0, shape=())

    def _rule(self, p, g, slots, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = slots["beta1_pow"] * b1
        b2p = slots["beta2_pow"] * b2
        from ..ops.pallas.fused_adam import adam_step
        new_p, m, v = adam_step(p, g, slots["moment1"], slots["moment2"],
                                lr, b1p, b2p, beta1=b1, beta2=b2, eps=eps,
                                use_fused=self._use_fused)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}

    _warned_unequal_beta_pow = False

    def _batched_update(self, params_grads, lr):
        """Multi-tensor path (reference adam_op.cu FusedAdamKernel):
        one Pallas dispatch updates every param. Shared beta-pow bias
        correction — see fused_adam_update_multi's semantics note. The
        shared correction is only valid when every live param has
        stepped in lockstep; unequal beta-pow slots (a param added
        mid-training, a partial checkpoint restore) warn once and fall
        back to the exact per-tensor loop."""
        use = self._use_multi_tensor
        if use is None:
            from ..ops import pallas as P
            use = P.enabled("fused_adam_multi")
        live = [(p, g) for p, g in params_grads if g is not None]
        if not use or len(live) < 2:
            return False
        from ..ops.pallas.fused_adam import fused_adam_update_multi
        for p, _ in live:
            self._pre_param(p)
        slots = [self._accumulators[id(p)] for p, _ in live]
        if not self._beta_pows_aligned(slots):
            if not Adam._warned_unequal_beta_pow:
                import warnings
                warnings.warn(
                    "multi-tensor Adam: live params' beta1_pow/beta2_pow "
                    "slots are not all equal (params stepped out of "
                    "lockstep); falling back to the exact per-tensor "
                    "update loop", RuntimeWarning)
                Adam._warned_unequal_beta_pow = True
            if _monitor.enabled():
                _monitor.counter(
                    "optimizer.adam_multi_tensor_fallback").inc()
            return False
        b1p = slots[0]["beta1_pow"].data * self._beta1
        b2p = slots[0]["beta2_pow"].data * self._beta2
        new_ps, new_ms, new_vs = fused_adam_update_multi(
            [p.data for p, _ in live], [g for _, g in live],
            [s["moment1"].data for s in slots],
            [s["moment2"].data for s in slots],
            lr, b1p, b2p, beta1=self._beta1, beta2=self._beta2,
            eps=self._eps, weight_decay=getattr(self, "_wd", 0.0))
        for (p, _), s, np_, nm, nv in zip(live, slots, new_ps, new_ms,
                                          new_vs):
            p.data = np_
            s["moment1"].data = nm
            s["moment2"].data = nv
            s["beta1_pow"].data = b1p
            s["beta2_pow"].data = b2p
        return True

    @staticmethod
    def _beta_pows_aligned(slots):
        """True when every live param's beta-pow pair matches slot 0's.
        Tracers (a step being traced by jit.to_static) can't be compared
        host-side — the traced loop keeps whatever layout it was traced
        with, so treat them as aligned."""
        vals = []
        for s in slots:
            pair = (s["beta1_pow"].data, s["beta2_pow"].data)
            if any(isinstance(v, jax.core.Tracer) for v in pair):
                return True
            vals.append((float(pair[0]), float(pair[1])))
        return all(v == vals[0] for v in vals[1:])

    def _arena_apply(self, arena, packed, lr):
        """Flat-arena update: one adam_step_flat call per dtype group,
        reading/writing the arena buffers in place — no per-step
        gather/scatter over the param set. Beta-pow bias correction is
        shared per group (multi-tensor semantics; arena packing already
        warned if adopted pows disagreed)."""
        from ..ops.pallas.fused_adam import adam_step_flat
        for grp, flat_g, mask in packed:
            m = grp.slots["moment1"]
            v = grp.slots["moment2"]
            b1p = grp.pows["beta1_pow"].data * jnp.asarray(
                self._beta1, grp.pows["beta1_pow"].data.dtype)
            b2p = grp.pows["beta2_pow"].data * jnp.asarray(
                self._beta2, grp.pows["beta2_pow"].data.dtype)
            new_p, new_m, new_v = adam_step_flat(
                grp.flat.data, flat_g, m.data, v.data, lr, b1p, b2p,
                beta1=self._beta1, beta2=self._beta2, eps=self._eps,
                weight_decay=getattr(self, "_wd", 0.0), mask=mask,
                use_fused=self._use_fused)
            grp.flat.data = new_p
            m.data = new_m
            v.data = new_v
            grp.pows["beta1_pow"].data = b1p
            grp.pows["beta2_pow"].data = b2p


class AdamW(Adam):
    """Decoupled weight decay (reference: AdamW in later paddle; also the
    natural TPU formulation — decay fuses into the same update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         **kw)
        self._wd = float(weight_decay) if not isinstance(
            weight_decay, WeightDecayRegularizer) else weight_decay.coeff
        self._regularization = None  # decoupled — not added to grad

    def _rule(self, p, g, slots, lr):
        new_p, new_slots = super()._rule(p, g, slots, lr)
        # cast back per term: a weak-typed f32 lr*wd*p would otherwise
        # promote bf16 params (and diverge from adam_step_flat's
        # cast-per-term sequence)
        new_p = (new_p - lr * self._wd * p).astype(p.dtype)
        return new_p, new_slots


class Adamax(Optimizer):
    """reference: AdamaxOptimizer / adamax_op.cc"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _pre_param(self, p):
        self._slot(p, "moment")
        self._slot(p, "inf_norm")
        self._slot(p, "beta1_pow", init=1.0, shape=())

    def _rule(self, p, g, slots, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = slots["beta1_pow"] * b1
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        new_p = p - lr / (1 - b1p) * m / (u + eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    """reference: LambOptimizer / lamb_op.cc — layer-adaptive Adam for
    large-batch BERT training."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _pre_param(self, p):
        self._slot(p, "moment1")
        self._slot(p, "moment2")
        self._slot(p, "beta1_pow", init=1.0, shape=())
        self._slot(p, "beta2_pow", init=1.0, shape=())
        self._current_param = p

    def _rule(self, p, g, slots, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = slots["beta1_pow"] * b1
        b2p = slots["beta2_pow"] * b2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(
                self._current_param):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        pn = jnp.sqrt(jnp.sum(jnp.square(p)))
        rn = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v,
                                    "beta1_pow": b1p, "beta2_pow": b2p}


class RMSProp(Optimizer):
    """reference: RMSPropOptimizer / rmsprop_op.cc"""

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _pre_param(self, p):
        self._slot(p, "mean_square")
        self._slot(p, "momentum")
        if self._centered:
            self._slot(p, "mean_grad")

    def _rule(self, p, g, slots, lr):
        rho, eps = self._rho, self._eps
        ms = rho * slots["mean_square"] + (1 - rho) * g * g
        new_slots = {"mean_square": ms}
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = ms - mg * mg + eps
            new_slots["mean_grad"] = mg
        else:
            denom = ms + eps
        mom = self._momentum * slots["momentum"] + lr * g / jnp.sqrt(denom)
        new_slots["momentum"] = mom
        return p - mom, new_slots


class Ftrl(Optimizer):
    """reference: FtrlOptimizer / ftrl_op.cc"""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _pre_param(self, p):
        self._slot(p, "squared")
        self._slot(p, "linear")

    def _rule(self, p, g, slots, lr):
        l1, l2, lrp = self._l1, self._l2, self._lr_power
        sq = slots["squared"]
        new_sq = sq + g * g
        sigma = (jnp.power(new_sq, -lrp) - jnp.power(
            jnp.maximum(sq, 1e-30), -lrp)) / lr
        lin = slots["linear"] + g - sigma * p
        pre = jnp.power(new_sq, -lrp) / lr + 2 * l2
        x = l1 * jnp.sign(lin) - lin
        new_p = jnp.where(jnp.abs(lin) > l1, x / pre, 0.0)
        return new_p, {"squared": new_sq, "linear": lin}


class Dpsgd(Optimizer):
    """reference: DpsgdOptimizer / dpsgd_op.cc — differentially-private SGD
    (clip + gaussian noise)."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16,
                 sigma=1.0, parameters=None, **kw):
        super().__init__(learning_rate, parameters, **kw)
        self._clip = clip
        self._batch_size = batch_size
        self._sigma = sigma

    def _rule(self, p, g, slots, lr):
        from .. import random as prandom
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        g = g / jnp.maximum(1.0, gn / self._clip)
        noise = jax.random.normal(prandom.next_key(), g.shape,
                                  g.dtype) * self._sigma * self._clip
        g = (g + noise) / self._batch_size
        return p - lr * g, {}


# ---------------------------------------------------------------------------
# meta-optimizers / wrappers

class ExponentialMovingAverage:
    """reference: optimizer.py:ExponentialMovingAverage — shadow weights with
    apply()/restore() context."""

    def __init__(self, decay=0.999, thres_steps=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0
        self._params = None

    def update(self, parameters=None):
        if parameters is not None:
            self._params = list(parameters)
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            pid = id(p)
            if pid not in self._shadow:
                self._shadow[pid] = p.data
            else:
                self._shadow[pid] = d * self._shadow[pid] + (1 - d) * p.data

    def apply(self, parameters=None):
        params = list(parameters) if parameters is not None else self._params
        for p in params:
            self._backup[id(p)] = p.data
            if id(p) in self._shadow:
                p.data = self._shadow[id(p)]
        return _EMAGuard(self, params)

    def restore(self, parameters=None):
        params = list(parameters) if parameters is not None else self._params
        for p in params:
            if id(p) in self._backup:
                p.data = self._backup.pop(id(p))


class _EMAGuard:
    def __init__(self, ema, params):
        self._ema, self._params = ema, params

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ema.restore(self._params)


class ModelAverage(ExponentialMovingAverage):
    """reference: optimizer.py:ModelAverage — running average of weights over
    a window; same apply/restore protocol."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000):
        super().__init__(decay=0.0)
        self._sum = {}
        self._count = {}
        self._max_window = max_average_window

    def update(self, parameters=None):
        if parameters is not None:
            self._params = list(parameters)
        for p in self._params:
            pid = id(p)
            if pid not in self._sum or self._count[pid] >= self._max_window:
                self._sum[pid] = p.data
                self._count[pid] = 1
            else:
                self._sum[pid] = self._sum[pid] + p.data
                self._count[pid] += 1
            self._shadow[pid] = self._sum[pid] / self._count[pid]


class LookAhead:
    """reference: LookaheadOptimizer — slow/fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner = inner_optimizer
        self._alpha = alpha
        self._k = k
        self._step = 0
        self._slow = {}

    def step(self):
        self.inner.step()
        self._step += 1
        if self._step % self._k == 0:
            for p in self.inner._params():
                pid = id(p)
                slow = self._slow.get(pid, p.data)
                if pid not in self._slow:
                    self._slow[pid] = p.data
                    continue
                slow = slow + self._alpha * (p.data - slow)
                self._slow[pid] = slow
                p.data = slow

    def minimize(self, loss, **kw):
        if loss is not None and loss._tape_node is not None:
            loss.backward()
        self.step()

    def clear_grad(self):
        self.inner.clear_grad()

    clear_gradients = clear_grad


class RecomputeOptimizer:
    """reference: RecomputeOptimizer — gradient checkpointing. On TPU this
    is `jax.checkpoint` applied to the forward segments; use
    paddle_tpu.jit.recompute(fn) on the blocks to rematerialize, then train
    with the inner optimizer as usual."""

    def __init__(self, optimizer):
        self.inner = optimizer

    def __getattr__(self, item):
        return getattr(self.inner, item)


# fluid-era aliases (reference exports *Optimizer names)
SGDOptimizer = SGD
MomentumOptimizer = Momentum
LarsMomentumOptimizer = LarsMomentum
AdagradOptimizer = Adagrad
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
LambOptimizer = Lamb
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
DpsgdOptimizer = Dpsgd
LookaheadOptimizer = LookAhead


class PipelineOptimizer:
    """reference: optimizer.py:PipelineOptimizer — pipeline-parallel
    training. On TPU, pipeline parallelism is a mesh axis, not an optimizer
    wrapper: see paddle_tpu.parallel.megatron (GPipe microbatch ring over
    ppermute). This class keeps API parity and delegates stepping to the
    inner optimizer."""

    def __init__(self, optimizer, num_microbatches=1, **kw):
        self.inner = optimizer
        self.num_microbatches = num_microbatches

    def __getattr__(self, item):
        return getattr(self.inner, item)
