"""paddle_tpu.compat — python 2/3 compatibility helpers.

Reference: python/paddle/compat.py. The reference bridged py2/py3 string
and arithmetic semantics; on py3-only this reduces to thin, faithful
implementations of the same API (kept because user code and the fluid
data pipelines call them).
"""
import math

__all__ = [
    "long_type", "to_text", "to_bytes", "round", "floor_division",
    "get_exception_message",
]

long_type = int


def _convert(obj, conv, inplace):
    if obj is None:
        return obj
    if isinstance(obj, dict):
        # Reference converts both keys and values (compat.py dict
        # branch); keys are always freshly converted (can't mutate in
        # place), values honor inplace for the dict itself.
        items = {_convert(k, conv, False): _convert(v, conv, False)
                 for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(items)
            return obj
        return items
    if isinstance(obj, (list, set)):
        if inplace:
            items = [_convert(o, conv, False) for o in obj]
            obj.clear()
            if isinstance(obj, list):
                obj.extend(items)
            else:
                obj.update(items)
            return obj
        return type(obj)(_convert(o, conv, False) for o in obj)
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """Convert bytes (or a list/set of mixed values) to text; values
    that are neither str nor bytes pass through unchanged. Reference:
    compat._to_text."""
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else o
    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Convert str (or a list/set of them) to bytes; bytes pass
    through; anything else raises like the reference's six.b path —
    silently NUL-filling via bytes(int) would corrupt data."""
    def conv(o):
        if isinstance(o, str):
            return o.encode(encoding)
        if isinstance(o, bytes):
            return o
        raise TypeError(
            f"to_bytes expects str/bytes, got {type(o).__name__}")
    return _convert(obj, conv, inplace)


def round(x, d=0):
    """Python-2-style round (half away from zero). Reference:
    compat.round — py3's banker's rounding differs at .5 boundaries."""
    if x is None:
        return None
    p = 10 ** d
    if x >= 0:
        return float(math.floor((x * p) + 0.5)) / p
    return float(math.ceil((x * p) - 0.5)) / p


def floor_division(x, y):
    """reference: compat.floor_division — explicit // for mixed py2/py3
    call sites."""
    return x // y


def get_exception_message(exc):
    """reference: compat.get_exception_message."""
    assert exc is not None
    return str(exc)
