"""paddle_tpu.dispatch — the single op-dispatch point.

TPU-native rebuild of the reference's operator dispatch
(reference: paddle/fluid/imperative/tracer.cc TraceOp for dygraph;
python/paddle/fluid/framework.py append_op for static graph). Every
functional op in paddle_tpu.ops funnels through :func:`apply`:

* **dygraph** (default): run the pure-jax impl eagerly; when grad is
  required, run it under ``jax.vjp`` and record a TapeNode.
* **static graph**: append an OpNode carrying the same pure-jax impl to the
  current Program; the Executor later interprets the whole graph under one
  ``jax.jit`` (the XLA analogue of the reference's C++ executor loop).

Because impls are pure jax functions, the same code path works on eager
arrays and on tracers — ``jit.to_static`` simply traces the dygraph path.
"""
from __future__ import annotations

import time as _time

import jax

from .tensor import Tensor, as_tensor
from . import autograd
from .autograd import TapeNode

# Static-graph hook, installed by paddle_tpu.static to avoid a circular
# import. When non-None and static mode is on, apply() records graph nodes.
_static_recorder = None
_in_static_mode = False

# Monitor hook, installed by paddle_tpu.monitor.enable(). None (the
# default) keeps the fast path at a single `is None` check — the
# disabled-mode cost contract asserted by tests/test_monitor.py.
# With time_ops, the hook's t0 stamp also feeds per-op `dispatch.<op>`
# complete events into monitor.trace (the span timeline reuses the one
# perf_counter() pair time_dispatch already pays — no extra cost here).
_monitor_hook = None
_monitor_time = False


def install_monitor_hook(fn, time_ops=False):
    """fn(name, grad, t0, static=False) or None to uninstall. With
    time_ops, apply() stamps t0 before running the impl so the hook can
    histogram host-side dispatch latency."""
    global _monitor_hook, _monitor_time
    _monitor_hook = fn
    _monitor_time = bool(time_ops) and fn is not None


def set_static_mode(flag):
    global _in_static_mode
    _in_static_mode = flag


def in_static_mode():
    return _in_static_mode


def install_static_recorder(fn):
    global _static_recorder
    _static_recorder = fn


def apply(impl, tensors, attrs=None, nondiff=False, n_out=1, name=""):
    """Dispatch one op.

    impl: pure function (*jax_arrays, **attrs) -> array | tuple of arrays
    tensors: the differentiable positional inputs (Tensor or array-likes)
    attrs: static keyword attrs baked into the op
    nondiff: output carries no gradient (argmax, comparisons, ...)
    """
    attrs = attrs or {}
    hook = _monitor_hook  # the single flag check on the disabled path
    if _in_static_mode and _static_recorder is not None:
        if hook is not None:
            hook(name, False, None, static=True)
        return _static_recorder(impl, tensors, attrs, nondiff, n_out, name)
    if hook is not None:
        t0 = _time.perf_counter() if _monitor_time else None

    ts = [as_tensor(t) for t in tensors]
    arrays = [t.data for t in ts]

    need_grad = (not nondiff and autograd.grad_enabled()
                 and any(not t.stop_gradient for t in ts))

    if need_grad:
        outs, vjp = jax.vjp(lambda *xs: impl(*xs, **attrs), *arrays)
    else:
        outs = impl(*arrays, **attrs)

    single = not isinstance(outs, (tuple, list))
    outs_seq = (outs,) if single else tuple(outs)
    out_tensors = tuple(Tensor(o, stop_gradient=not need_grad)
                        for o in outs_seq)

    if need_grad:
        node = TapeNode(ts, vjp, list(out_tensors), name=name)
        for ot in out_tensors:
            ot._tape_node = node

    if hook is not None:
        hook(name, need_grad, t0)

    return out_tensors[0] if single else out_tensors
