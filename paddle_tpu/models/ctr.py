"""CTR models: Wide&Deep and DeepFM (reference: the fluid parameter-server
CTR examples under fluid/incubate/fleet/parameter_server + PaddleRec-era
configs — sparse lookup_table + fc tower trained via the distributed
transpiler).

TPU-first redesign: there is no parameter server — the big embedding tables
are *mesh-sharded* (parallel.embedding.ShardedEmbedding shards rows over a
mesh axis and resolves lookups with collectives), and training is pure
data-parallel all-reduce. Dense towers are ordinary MXU matmuls.
"""
from __future__ import annotations

import numpy as np

from .. import nn, ops
from ..ops import nn_ops as F


class SparseFeatureEmbedding(nn.Layer):
    """Embedding for ID features; swaps in a sharded table when a mesh is
    active and `sharded=True` (the PS replacement)."""

    def __init__(self, num_embeddings, embedding_dim, sharded=False,
                 axis_name="mp"):
        super().__init__()
        if sharded:
            from ..parallel.embedding import ShardedEmbedding
            self.table = ShardedEmbedding(num_embeddings, embedding_dim,
                                          axis_name=axis_name)
        else:
            self.table = nn.Embedding(num_embeddings, embedding_dim)

    def forward(self, ids):
        return self.table(ids)


class WideDeep(nn.Layer):
    """Wide (linear over sparse ids) + Deep (embeddings -> MLP)."""

    def __init__(self, sparse_feature_number=10000, sparse_num_field=26,
                 dense_feature_dim=13, embedding_size=16,
                 layer_sizes=(400, 400, 400), sharded=False):
        super().__init__()
        self.wide = SparseFeatureEmbedding(sparse_feature_number, 1,
                                           sharded=sharded)
        self.embedding = SparseFeatureEmbedding(sparse_feature_number,
                                                embedding_size,
                                                sharded=sharded)
        dims = [sparse_num_field * embedding_size + dense_feature_dim] + \
            list(layer_sizes)
        mlp = []
        for i in range(len(layer_sizes)):
            mlp += [nn.Linear(dims[i], dims[i + 1]), nn.ReLU()]
        mlp.append(nn.Linear(dims[-1], 1))
        self.deep = nn.Sequential(*mlp)
        self.dense_fc = nn.Linear(dense_feature_dim, dense_feature_dim)

    def forward(self, sparse_ids, dense_features):
        # sparse_ids: [B, F] int ids; dense: [B, D]
        wide_logit = self.wide(sparse_ids).squeeze(-1).sum(axis=1,
                                                           keepdim=True)
        emb = self.embedding(sparse_ids).flatten(1)
        deep_in = ops.concat([emb, F.relu(self.dense_fc(dense_features))],
                             axis=1)
        deep_logit = self.deep(deep_in)
        return wide_logit + deep_logit

    def loss(self, logit, label):
        return ops.loss.binary_cross_entropy_with_logits(
            logit, label.astype("float32").reshape(logit.shape))


class DeepFM(nn.Layer):
    """FM (1st + 2nd order) + deep tower (reference PaddleRec deepfm)."""

    def __init__(self, sparse_feature_number=10000, sparse_num_field=26,
                 dense_feature_dim=13, embedding_size=16,
                 layer_sizes=(400, 400, 400), sharded=False):
        super().__init__()
        self.first_order = SparseFeatureEmbedding(sparse_feature_number, 1,
                                                  sharded=sharded)
        self.embedding = SparseFeatureEmbedding(sparse_feature_number,
                                                embedding_size,
                                                sharded=sharded)
        self.dense_w = self.create_parameter((1, dense_feature_dim))
        dims = [sparse_num_field * embedding_size + dense_feature_dim] + \
            list(layer_sizes)
        mlp = []
        for i in range(len(layer_sizes)):
            mlp += [nn.Linear(dims[i], dims[i + 1]), nn.ReLU()]
        mlp.append(nn.Linear(dims[-1], 1))
        self.deep = nn.Sequential(*mlp)

    def forward(self, sparse_ids, dense_features):
        first = self.first_order(sparse_ids).squeeze(-1).sum(
            axis=1, keepdim=True)
        first = first + (dense_features * self.dense_w).sum(axis=1,
                                                            keepdim=True)
        emb = self.embedding(sparse_ids)  # [B, F, K]
        sum_sq = emb.sum(axis=1).square()
        sq_sum = emb.square().sum(axis=1)
        second = 0.5 * (sum_sq - sq_sum).sum(axis=1, keepdim=True)
        deep_in = ops.concat([emb.flatten(1), dense_features], axis=1)
        deep = self.deep(deep_in)
        return first + second + deep

    def loss(self, logit, label):
        return ops.loss.binary_cross_entropy_with_logits(
            logit, label.astype("float32").reshape(logit.shape))
