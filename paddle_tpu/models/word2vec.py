"""word2vec skip-gram with negative sampling (reference: the Book word2vec
chapter + fluid distributed word2vec example using nce/lookup_table)."""
from __future__ import annotations

import numpy as np

from .. import nn, ops
from ..ops import nn_ops as F


class SkipGram(nn.Layer):
    def __init__(self, vocab_size=10000, embedding_dim=128, neg_num=5):
        super().__init__()
        self.emb_in = nn.Embedding(vocab_size, embedding_dim)
        self.emb_out = nn.Embedding(vocab_size, embedding_dim)
        self.neg_num = neg_num
        self.vocab_size = vocab_size

    def forward(self, center, target, label):
        """center,target: [B] ids; label: [B] 1 for true pair, 0 for
        negative (reference feeds pre-sampled negatives)."""
        c = self.emb_in(center)
        t = self.emb_out(target)
        logit = (c * t).sum(axis=-1)
        return ops.loss.binary_cross_entropy_with_logits(
            logit, label.astype("float32"))

    def train_batch_loss(self, center, context):
        """Convenience: sample neg_num negatives uniformly per positive."""
        b = center.shape[0]
        neg = ops.randint(0, self.vocab_size, (b * self.neg_num,))
        centers = ops.concat([center] * (1 + self.neg_num), axis=0)
        targets = ops.concat([context, neg], axis=0)
        labels = ops.concat([ops.ones((b,)), ops.zeros((b * self.neg_num,))],
                            axis=0)
        return self.forward(centers, targets, labels)


Word2Vec = SkipGram
