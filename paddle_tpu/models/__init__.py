"""paddle_tpu.models — the model zoo.

TPU-native rebuild of the reference's flagship models (reference: the Book
chapters + fluid/tests configs: LeNet/MNIST, VGG, ResNet-50, MobileNet,
BERT, Transformer (WMT), Wide&Deep, DeepFM, word2vec).
"""
from .lenet import LeNet

__all__ = ["LeNet"]


def __getattr__(name):
    # lazy imports keep `import paddle_tpu` light
    if name in ("ResNet", "resnet50", "resnet18", "resnet34", "resnet101",
                "resnet152"):
        from . import resnet
        return getattr(resnet, name)
    if name in ("VGG", "vgg16", "vgg19"):
        from . import vgg
        return getattr(vgg, name)
    if name in ("MobileNetV1", "MobileNetV2"):
        from . import mobilenet
        return getattr(mobilenet, name)
    if name in ("Bert", "BertConfig", "BertForPretraining"):
        from . import bert
        return getattr(bert, name)
    if name in ("Transformer",):
        from . import transformer
        return getattr(transformer, name)
    if name in ("WideDeep", "DeepFM"):
        from . import ctr
        return getattr(ctr, name)
    if name in ("Word2Vec", "SkipGram"):
        from . import word2vec
        return getattr(word2vec, name)
    if name in ("YOLOv3", "SSD"):
        from . import detection
        return getattr(detection, name)
    if name in ("SEResNeXt", "se_resnext50", "se_resnext101"):
        from . import se_resnext
        return getattr(se_resnext, name)
    raise AttributeError(name)
