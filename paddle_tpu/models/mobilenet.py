"""MobileNet V1/V2 (reference: fluid benchmark configs mobilenet_ssd /
image classification mobilenet).

TPU note: depthwise convs map to feature_group_count convolutions; XLA
lowers them efficiently, though they are HBM-bound rather than MXU-bound.
"""
from __future__ import annotations

from .. import nn


def _conv_bn(cin, cout, k, stride=1, padding=0, groups=1, relu6=True):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(cout),
        nn.ReLU6() if relu6 else nn.ReLU(),
    )


class MobileNetV1(nn.Layer):
    def __init__(self, num_classes=1000, scale=1.0, in_channels=3):
        super().__init__()
        def c(ch):
            return max(8, int(ch * scale))
        cfg = [(c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
               (c(128), c(256), 2), (c(256), c(256), 1), (c(256), c(512), 2)]
        cfg += [(c(512), c(512), 1)] * 5
        cfg += [(c(512), c(1024), 2), (c(1024), c(1024), 1)]
        layers = [_conv_bn(in_channels, c(32), 3, stride=2, padding=1,
                           relu6=False)]
        for cin, cout, s in cfg:
            layers.append(_conv_bn(cin, cin, 3, stride=s, padding=1,
                                   groups=cin, relu6=False))  # depthwise
            layers.append(_conv_bn(cin, cout, 1, relu6=False))  # pointwise
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(x.flatten(1))


class InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hidden = cin * expand
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(_conv_bn(cin, hidden, 1))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, padding=1,
                     groups=hidden),
            nn.Conv2D(hidden, cout, 1, bias_attr=False),
            nn.BatchNorm2D(cout),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, num_classes=1000, scale=1.0, in_channels=3):
        super().__init__()
        def c(ch):
            return max(8, int(ch * scale))
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        layers = [_conv_bn(in_channels, c(32), 3, stride=2, padding=1)]
        cin = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(InvertedResidual(cin, c(ch),
                                               s if i == 0 else 1, t))
                cin = c(ch)
        layers.append(_conv_bn(cin, c(1280), 1))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(c(1280), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(x.flatten(1))
