"""BERT (reference: the ERNIE/BERT fluid implementations used with this
Paddle generation — static Program transformer encoder with
fused layer_norm + softmax_with_cross_entropy; see also
paddle/fluid/operators/fused/ for the fused kernels it relied on).

TPU-first rebuild: one jitted train step; attention is a batched einsum
(MXU) with optional Pallas flash-attention; bf16 compute via amp; the
sequence axis can be sharded for long-context (parallel.ring_attention).
"""
from __future__ import annotations

import math

import numpy as np

from .. import nn, ops
from ..ops import nn_ops as F


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 layer_norm_eps=1e-12, use_flash_attention=True,
                 use_recompute=False, moe_num_experts=0, moe_every=2,
                 moe_capacity_factor=1.25):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention
        # rematerialize each encoder layer's activations during backward
        # (jax.checkpoint) — the long-context memory knob
        self.use_recompute = use_recompute
        # moe_num_experts > 0 swaps every `moe_every`-th layer's FFN for an
        # expert-parallel nn.MoEFFN (sharded over the mesh's ep axis under
        # fleet.distributed_model)
        self.moe_num_experts = moe_num_experts
        self.moe_every = moe_every
        self.moe_capacity_factor = moe_capacity_factor

    @staticmethod
    def base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        d = dict(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                 num_attention_heads=2, intermediate_size=512,
                 max_position_embeddings=128)
        d.update(kw)
        return BertConfig(**d)


class MultiHeadAttention(nn.Layer):
    """Self-attention: fused QKV projection (one MXU matmul) + sdpa."""

    def __init__(self, config: BertConfig):
        super().__init__()
        d = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = d // self.num_heads
        self.qkv = nn.Linear(d, 3 * d)
        self.out = nn.Linear(d, d)
        self.dropout_p = config.attention_probs_dropout_prob
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_mask=None):
        b, s, d = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        qkv = qkv.transpose([2, 0, 3, 1, 4])  # 3, B, H, S, D
        q, k, v = qkv[0], qkv[1], qkv[2]
        if self.use_flash:
            from ..ops.pallas import flash_attention
            ctx = flash_attention(q, k, v, attn_mask=attn_mask,
                                  dropout_p=self.dropout_p,
                                  training=self.training)
        else:
            ctx = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.dropout_p,
                training=self.training)
        ctx = ctx.transpose([0, 2, 1, 3]).reshape([b, s, d])
        return self.out(ctx)


class TransformerEncoderLayer(nn.Layer):
    def __init__(self, config: BertConfig, layer_idx=0):
        super().__init__()
        d = config.hidden_size
        self.attention = MultiHeadAttention(config)
        self.attn_norm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)
        self.moe = None
        if config.moe_num_experts > 0 and \
                (layer_idx + 1) % max(1, config.moe_every) == 0:
            self.moe = nn.MoEFFN(d, config.intermediate_size,
                                 config.moe_num_experts,
                                 config.moe_capacity_factor)
        else:
            self.ffn1 = nn.Linear(d, config.intermediate_size)
            self.ffn2 = nn.Linear(config.intermediate_size, d)
        self.ffn_norm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.attn_norm(x + self.dropout(self.attention(x, attn_mask)))
        if self.moe is not None:
            h = self.moe(x)
        else:
            h = self.ffn2(F.gelu(self.ffn1(x)))
        return self.ffn_norm(x + self.dropout(h))


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        d = config.hidden_size
        self.word_embeddings = nn.Embedding(config.vocab_size, d)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, d)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, d)
        self.norm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.norm(emb))


class Bert(nn.Layer):
    """Encoder stack + pooler (reference ERNIE/BERT encoder)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [TransformerEncoderLayer(config, layer_idx=i)
             for i in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        if attention_mask is not None:
            # [B, S] -> additive [B, 1, 1, S]
            am = (1.0 - attention_mask.astype("float32")) * -1e9
            am = am.unsqueeze(1).unsqueeze(1)
        else:
            am = None
        x = self.embeddings(input_ids, token_type_ids)
        if isinstance(self.encoder, nn.LayerList):
            if getattr(self.config, "use_recompute", False):
                from .. import jit as _jit
                for layer in self.encoder:
                    x = _jit.recompute(layer, x, am)
            else:
                for layer in self.encoder:
                    x = layer(x, am)
        else:
            # e.g. parallel.pipeline.PipelineStack replacing the trunk
            x = self.encoder(x, am) if am is not None else self.encoder(x)
        pooled = ops.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference: the train.py of the fluid BERT repo)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = Bert(config)
        d = config.hidden_size
        self.mlm_transform = nn.Linear(d, d)
        self.mlm_norm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)
        self.mlm_bias = self.create_parameter((config.vocab_size,),
                                              is_bias=True)
        self.nsp = nn.Linear(d, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        # tied output embedding: reuse word embedding table (one big MXU gemm)
        logits = ops.matmul(
            h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True) + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return logits, nsp_logits

    def loss(self, logits, nsp_logits, mlm_labels, nsp_labels,
             ignore_index=-1):
        mlm = ops.loss.cross_entropy(logits, mlm_labels,
                                     ignore_index=ignore_index)
        nsp = ops.loss.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp
