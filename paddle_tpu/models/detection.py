"""Detection model zoo: YOLOv3 and SSD.

Rebuild of the reference detection pipelines (reference: the YOLOv3 /
SSD configs the fluid detection ops serve —
python/paddle/fluid/layers/detection.py yolov3_loss:912 / yolo_box:1038 /
ssd_loss:1410 / detection_output:541 / multi_box_head:1991; models in
the era's PaddleDetection used exactly these ops).

TPU-first: whole train step jits (static-shape padded gt boxes), NMS is
the fixed-size top-k formulation, convs are NCHW MXU convolutions with
BN+ReLU fused by XLA.
"""
from __future__ import annotations

import numpy as np

from .. import nn, ops
from ..ops import detection as D
from ..ops import nn_ops as F

__all__ = ["YOLOv3", "SSD", "DEFAULT_ANCHORS", "DEFAULT_ANCHOR_MASKS"]

DEFAULT_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                   116, 90, 156, 198, 373, 326]
DEFAULT_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


def _conv_bn(cin, cout, k=3, stride=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                  bias_attr=False),
        nn.BatchNorm2D(cout),
        nn.LeakyReLU(0.1),
    )


class _DarkNetTiny(nn.Layer):
    """Small darknet-style backbone emitting 3 scales (C3, C4, C5)."""

    def __init__(self, width=32):
        super().__init__()
        w = width
        self.stem = _conv_bn(3, w, 3)
        self.down1 = _conv_bn(w, w * 2, 3, stride=2)      # /2
        self.block1 = _conv_bn(w * 2, w * 2, 3)
        self.down2 = _conv_bn(w * 2, w * 4, 3, stride=2)  # /4
        self.block2 = _conv_bn(w * 4, w * 4, 3)
        self.down3 = _conv_bn(w * 4, w * 8, 3, stride=2)  # /8 → C3
        self.block3 = _conv_bn(w * 8, w * 8, 3)
        self.down4 = _conv_bn(w * 8, w * 16, 3, stride=2)  # /16 → C4
        self.block4 = _conv_bn(w * 16, w * 16, 3)
        self.down5 = _conv_bn(w * 16, w * 32, 3, stride=2)  # /32 → C5
        self.block5 = _conv_bn(w * 32, w * 32, 3)

    def forward(self, x):
        x = self.block1(self.down1(self.stem(x)))
        x = self.block2(self.down2(x))
        c3 = self.block3(self.down3(x))
        c4 = self.block4(self.down4(c3))
        c5 = self.block5(self.down5(c4))
        return c3, c4, c5


class YOLOv3(nn.Layer):
    """YOLOv3 with a compact darknet backbone. forward → 3 raw head
    outputs (N, A*(5+C), H, W) at strides 32/16/8; `loss` applies
    yolov3_loss per scale; `predict` decodes + multiclass-NMS."""

    def __init__(self, num_classes=80, anchors=None, anchor_masks=None,
                 width=32):
        super().__init__()
        self.num_classes = num_classes
        self.anchors = anchors or list(DEFAULT_ANCHORS)
        self.anchor_masks = anchor_masks or [list(m) for m in
                                             DEFAULT_ANCHOR_MASKS]
        self.backbone = _DarkNetTiny(width)
        w = width
        chans = [w * 32, w * 16, w * 8]
        heads = []
        for i, mask in enumerate(self.anchor_masks):
            cout = len(mask) * (5 + num_classes)
            heads.append(nn.Sequential(
                _conv_bn(chans[i], chans[i] // 2, 1),
                nn.Conv2D(chans[i] // 2, cout, 1),
            ))
        self.heads = nn.LayerList(heads)
        self.downsamples = [32, 16, 8]

    def forward(self, x):
        c3, c4, c5 = self.backbone(x)
        feats = [c5, c4, c3]
        return [head(f) for head, f in zip(self.heads, feats)]

    def loss(self, outputs, gt_box, gt_label, gt_score=None,
             ignore_thresh=0.7):
        total = None
        for out, mask, ds in zip(outputs, self.anchor_masks,
                                 self.downsamples):
            l = D.yolov3_loss(out, gt_box, gt_label, self.anchors, mask,
                              self.num_classes, ignore_thresh, ds,
                              gt_score=gt_score).sum()
            total = l if total is None else total + l
        return total

    def predict(self, outputs, img_size, conf_thresh=0.01,
                nms_threshold=0.45, nms_top_k=400, keep_top_k=100):
        boxes_all, scores_all = [], []
        for out, mask, ds in zip(outputs, self.anchor_masks,
                                 self.downsamples):
            sub_anchors = []
            for m in mask:
                sub_anchors += self.anchors[2 * m:2 * m + 2]
            b, s = D.yolo_box(out, img_size, sub_anchors,
                              self.num_classes, conf_thresh, ds)
            boxes_all.append(b)
            scores_all.append(s)
        boxes = ops.concat(boxes_all, axis=1)
        scores = ops.concat(scores_all, axis=1)
        # (N, M, C) → (N, C, M) for multiclass_nms
        scores = scores.transpose([0, 2, 1])
        return D.multiclass_nms(boxes, scores, conf_thresh, nms_top_k,
                                keep_top_k, nms_threshold,
                                background_label=-1)


class SSD(nn.Layer):
    """SSD over a compact VGG-ish backbone: per-scale loc/conf heads +
    priors; `loss` = ssd_loss, `predict` = detection_output."""

    def __init__(self, num_classes=21, image_size=128, width=32):
        super().__init__()
        self.num_classes = num_classes
        self.image_size = image_size
        w = width
        self.stage1 = nn.Sequential(
            _conv_bn(3, w), _conv_bn(w, w),
            nn.MaxPool2D(2, 2),
            _conv_bn(w, w * 2), _conv_bn(w * 2, w * 2),
            nn.MaxPool2D(2, 2),
            _conv_bn(w * 2, w * 4),
        )  # /4
        self.stage2 = nn.Sequential(
            nn.MaxPool2D(2, 2), _conv_bn(w * 4, w * 8))   # /8
        self.stage3 = nn.Sequential(
            nn.MaxPool2D(2, 2), _conv_bn(w * 8, w * 8))   # /16
        chans = [w * 4, w * 8, w * 8]
        self._scale_cfg = [
            dict(min_size=image_size * 0.1, max_size=image_size * 0.25),
            dict(min_size=image_size * 0.25, max_size=image_size * 0.5),
            dict(min_size=image_size * 0.5, max_size=image_size * 0.9),
        ]
        self.loc_heads = nn.LayerList()
        self.conf_heads = nn.LayerList()
        self._npriors = []
        for c in chans:
            npri = 4  # ar 1 (min), sqrt(min*max), 2, 1/2
            self._npriors.append(npri)
            self.loc_heads.append(nn.Conv2D(c, npri * 4, 3, padding=1))
            self.conf_heads.append(
                nn.Conv2D(c, npri * num_classes, 3, padding=1))

    def forward(self, x):
        f1 = self.stage1(x)
        f2 = self.stage2(f1)
        f3 = self.stage3(f2)
        feats = [f1, f2, f3]
        locs, confs, priors, pvars = [], [], [], []
        n = x.shape[0]
        for feat, loc_h, conf_h, cfg, npri in zip(
                feats, self.loc_heads, self.conf_heads, self._scale_cfg,
                self._npriors):
            loc = loc_h(feat).transpose([0, 2, 3, 1]).reshape([n, -1, 4])
            conf = conf_h(feat).transpose([0, 2, 3, 1]).reshape(
                [n, -1, self.num_classes])
            pb, pv = D.prior_box(
                feat, x, min_sizes=[cfg["min_size"]],
                max_sizes=[cfg["max_size"]], aspect_ratios=[2.0],
                flip=True, clip=True)
            locs.append(loc)
            confs.append(conf)
            priors.append(pb.reshape([-1, 4]))
            pvars.append(pv.reshape([-1, 4]))
        return (ops.concat(locs, 1), ops.concat(confs, 1),
                ops.concat(priors, 0), ops.concat(pvars, 0))

    def loss(self, locs, confs, priors, pvars, gt_box, gt_label):
        return D.ssd_loss(locs, confs, gt_box, gt_label, priors,
                          pvars).sum()

    def predict(self, locs, confs, priors, pvars, keep_top_k=100):
        return D.detection_output(locs, confs, priors, pvars,
                                  keep_top_k=keep_top_k)
