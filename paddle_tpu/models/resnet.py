"""ResNet family (reference: the fluid image-classification configs used by
the book / benchmarks, e.g. PaddleClas-era ResNet-50 in
python/paddle/fluid/tests + paddle/fluid/inference tests resnet50).

TPU notes: convs lower to single MXU convolutions; BN+ReLU fuse into the
conv epilogue under XLA. Train in bf16 via amp.auto_cast for the benchmark
path. Layout is NCHW at the API (reference parity) — XLA's TPU layout
assignment picks the internal layout.
"""
from __future__ import annotations

from .. import nn


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_channels, channels, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv0 = nn.Conv2D(in_channels, channels, 1, bias_attr=False,
                               **df)
        self.bn0 = nn.BatchNorm2D(channels, **df)
        self.conv1 = nn.Conv2D(channels, channels, 3, stride=stride,
                               padding=1, bias_attr=False, **df)
        self.bn1 = nn.BatchNorm2D(channels, **df)
        self.conv2 = nn.Conv2D(channels, channels * 4, 1, bias_attr=False,
                               **df)
        self.bn2 = nn.BatchNorm2D(channels * 4, **df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn0(self.conv0(x)))
        out = self.relu(self.bn1(self.conv1(out)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_channels, channels, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv0 = nn.Conv2D(in_channels, channels, 3, stride=stride,
                               padding=1, bias_attr=False, **df)
        self.bn0 = nn.BatchNorm2D(channels, **df)
        self.conv1 = nn.Conv2D(channels, channels, 3, padding=1,
                               bias_attr=False, **df)
        self.bn1 = nn.BatchNorm2D(channels, **df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn0(self.conv0(x)))
        out = self.bn1(self.conv1(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depths, num_classes=1000, in_channels=3,
                 data_format="NCHW"):
        super().__init__()
        self._df = data_format
        df = dict(data_format=data_format)
        self.stem = nn.Sequential(
            nn.Conv2D(in_channels, 64, 7, stride=2, padding=3,
                      bias_attr=False, **df),
            nn.BatchNorm2D(64, **df),
            nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1, **df),
        )
        self.in_ch = 64
        layers = []
        for i, (channels, n) in enumerate(zip([64, 128, 256, 512], depths)):
            stride = 1 if i == 0 else 2
            layers.append(self._make_layer(block, channels, n, stride))
        self.layers = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D(1, **df)
        self.flatten = nn.Flatten(1)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, channels, blocks, stride):
        df = dict(data_format=self._df)
        downsample = None
        if stride != 1 or self.in_ch != channels * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.in_ch, channels * block.expansion, 1,
                          stride=stride, bias_attr=False, **df),
                nn.BatchNorm2D(channels * block.expansion, **df),
            )
        layers = [block(self.in_ch, channels, stride, downsample, **df)]
        self.in_ch = channels * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.in_ch, channels, **df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.stem(x)
        x = self.layers(x)
        x = self.flatten(self.avgpool(x))
        return self.fc(x)


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)
