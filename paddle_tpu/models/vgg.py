"""VGG (reference: the Book image-classification chapter vgg_bn_drop /
fluid tests vgg16)."""
from __future__ import annotations

from .. import nn

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
         "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
         512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    def __init__(self, depth=16, num_classes=1000, batch_norm=True,
                 in_channels=3, image_size=224):
        super().__init__()
        layers = []
        c = in_channels
        for v in _CFGS[depth]:
            if v == "M":
                layers.append(nn.MaxPool2D(2, 2))
            else:
                layers.append(nn.Conv2D(c, v, 3, padding=1))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v))
                layers.append(nn.ReLU())
                c = v
        self.features = nn.Sequential(*layers)
        spatial = image_size // 32
        self.classifier = nn.Sequential(
            nn.Flatten(1),
            nn.Linear(512 * spatial * spatial, 4096), nn.ReLU(),
            nn.Dropout(0.5),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        return self.classifier(self.features(x))


def vgg16(num_classes=1000, **kw):
    return VGG(16, num_classes, **kw)


def vgg19(num_classes=1000, **kw):
    return VGG(19, num_classes, **kw)
