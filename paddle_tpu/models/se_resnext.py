"""SE-ResNeXt (reference: the fluid benchmark/dist-train model family —
python/paddle/fluid/tests/unittests/dist_se_resnext.py — grouped
bottlenecks + squeeze-and-excitation gating).

TPU notes: grouped 3x3 convs lower to one MXU conv with
feature_group_count=cardinality; the SE block's global-pool + two tiny
FCs fuse into the epilogue under XLA. NCHW at the API like the rest of
the zoo (data_format="NHWC" available for layout A/B on TPU)."""
from __future__ import annotations

from .. import nn, ops


class SEBlock(nn.Layer):
    """Squeeze-and-excitation: global-avg-pool -> fc/r -> relu -> fc ->
    sigmoid channel gate."""

    def __init__(self, channels, reduction=16, data_format="NCHW"):
        super().__init__()
        self._df = data_format
        mid = max(channels // reduction, 4)
        self.squeeze = nn.Linear(channels, mid)
        self.excite = nn.Linear(mid, channels)

    def forward(self, x):
        axes = [2, 3] if self._df == "NCHW" else [1, 2]
        s = x.mean(axis=axes)                      # [N, C]
        s = ops.sigmoid(self.excite(ops.relu(self.squeeze(s))))
        if self._df == "NCHW":
            s = s.unsqueeze(-1).unsqueeze(-1)
        else:
            s = s.unsqueeze(1).unsqueeze(1)
        return x * s


class SEResNeXtBottleneck(nn.Layer):
    expansion = 2

    def __init__(self, in_channels, channels, stride=1, cardinality=32,
                 reduction=16, downsample=None, data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv0 = nn.Conv2D(in_channels, channels, 1, bias_attr=False,
                               **df)
        self.bn0 = nn.BatchNorm2D(channels, **df)
        self.conv1 = nn.Conv2D(channels, channels, 3, stride=stride,
                               padding=1, groups=cardinality,
                               bias_attr=False, **df)
        self.bn1 = nn.BatchNorm2D(channels, **df)
        self.conv2 = nn.Conv2D(channels, channels * self.expansion, 1,
                               bias_attr=False, **df)
        self.bn2 = nn.BatchNorm2D(channels * self.expansion, **df)
        self.se = SEBlock(channels * self.expansion, reduction,
                          data_format=data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn0(self.conv0(x)))
        out = self.relu(self.bn1(self.conv1(out)))
        out = self.se(self.bn2(self.conv2(out)))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class SEResNeXt(nn.Layer):
    """depths e.g. [3, 4, 6, 3] (50-layer) / [3, 4, 23, 3] (101)."""

    def __init__(self, depths, num_classes=1000, cardinality=32,
                 data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False,
                      **df),
            nn.BatchNorm2D(64, **df), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1, **df))
        in_ch = 64
        stages = []
        channels = 128
        for si, depth in enumerate(depths):
            blocks = []
            for bi in range(depth):
                stride = 2 if (si > 0 and bi == 0) else 1
                downsample = None
                out_ch = channels * SEResNeXtBottleneck.expansion
                if stride != 1 or in_ch != out_ch:
                    downsample = nn.Sequential(
                        nn.Conv2D(in_ch, out_ch, 1, stride=stride,
                                  bias_attr=False, **df),
                        nn.BatchNorm2D(out_ch, **df))
                blocks.append(SEResNeXtBottleneck(
                    in_ch, channels, stride=stride,
                    cardinality=cardinality, downsample=downsample,
                    data_format=data_format))
                in_ch = out_ch
            stages.append(nn.Sequential(*blocks))
            channels *= 2
        self.stages = nn.Sequential(*stages)
        self._df = data_format
        self.head = nn.Linear(in_ch, num_classes)

    def forward(self, x):
        x = self.stages(self.stem(x))
        axes = [2, 3] if self._df == "NCHW" else [1, 2]
        return self.head(x.mean(axis=axes))


def se_resnext50(num_classes=1000, **kw):
    return SEResNeXt([3, 4, 6, 3], num_classes, **kw)


def se_resnext101(num_classes=1000, **kw):
    return SEResNeXt([3, 4, 23, 3], num_classes, **kw)
