"""Transformer seq2seq for WMT en-de (reference: the fluid Transformer
"big"/"base" machine-translation model — static Program with fused
layer_norm, label_smooth + softmax_with_cross_entropy; e.g.
fluid/tests/../transformer configs).

TPU-first rebuild: pre-norm encoder-decoder, einsum attention on the MXU,
lax.scan-free (full teacher forcing in one computation), label smoothing
fused into the loss.
"""
from __future__ import annotations

import numpy as np

from .. import nn, ops
from ..ops import nn_ops as F


def sinusoid_position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("f4")
    i = np.arange(d_model // 2)[None, :].astype("f4")
    angle = pos / np.power(10000.0, 2 * i / d_model)
    enc = np.zeros((max_len, d_model), "f4")
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class CrossAttention(nn.Layer):
    def __init__(self, d_model, num_heads, dropout=0.1):
        super().__init__()
        self.h = num_heads
        self.dk = d_model // num_heads
        self.q_proj = nn.Linear(d_model, d_model)
        self.kv_proj = nn.Linear(d_model, 2 * d_model)
        self.out = nn.Linear(d_model, d_model)
        self.dropout_p = dropout

    def forward(self, q_in, kv_in, mask=None, is_causal=False):
        b, sq, d = q_in.shape
        sk = kv_in.shape[1]
        q = self.q_proj(q_in).reshape([b, sq, self.h, self.dk]).transpose(
            [0, 2, 1, 3])
        kv = self.kv_proj(kv_in).reshape([b, sk, 2, self.h, self.dk])
        kv = kv.transpose([2, 0, 3, 1, 4])
        k, v = kv[0], kv[1]
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, is_causal=is_causal,
            dropout_p=self.dropout_p, training=self.training)
        return self.out(ctx.transpose([0, 2, 1, 3]).reshape([b, sq, d]))

    # -- incremental (KV-cached) decode path --------------------------------

    def precompute_kv(self, kv_in):
        """Cross-attention K/V from the encoder memory, computed ONCE per
        decode: returns raw [N, H, Sk, dk] arrays."""
        import jax.numpy as jnp
        b, sk = kv_in.shape[0], kv_in.shape[1]
        kv = self.kv_proj(kv_in).data.reshape(b, sk, 2, self.h, self.dk)
        kv = jnp.transpose(kv, (2, 0, 3, 1, 4))
        return kv[0], kv[1]

    def step_self(self, x1, ck, cv, pos):
        """One cached self-attention step. x1: Tensor [N, 1, D]; ck/cv:
        raw [N, H, T_max, dk] caches; pos: traced scalar. Returns
        (Tensor [N, 1, D], new_ck, new_cv)."""
        import jax
        import jax.numpy as jnp
        from ..tensor import Tensor as _T
        ck = getattr(ck, "data", ck)   # beam search re-wraps cache leaves
        cv = getattr(cv, "data", cv)
        n = x1.shape[0]
        q = self.q_proj(x1).data.reshape(n, 1, self.h, self.dk)
        q = jnp.transpose(q, (0, 2, 1, 3))                    # [N,H,1,dk]
        kv = self.kv_proj(x1).data.reshape(n, 1, 2, self.h, self.dk)
        k1 = jnp.transpose(kv[:, :, 0], (0, 2, 1, 3))         # [N,H,1,dk]
        v1 = jnp.transpose(kv[:, :, 1], (0, 2, 1, 3))
        ck = jax.lax.dynamic_update_slice(ck, k1, (0, 0, pos, 0))
        cv = jax.lax.dynamic_update_slice(cv, v1, (0, 0, pos, 0))
        s = jnp.einsum("nhqd,nhtd->nhqt", q, ck) / np.sqrt(self.dk)
        t_max = ck.shape[2]
        valid = jnp.arange(t_max) <= pos
        s = jnp.where(valid[None, None, None, :], s, -1e9)
        p = jax.nn.softmax(s, axis=-1)
        ctx = jnp.einsum("nhqt,nhtd->nhqd", p, cv)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(n, 1, -1)
        return self.out(_T(ctx)), ck, cv

    def step_cross(self, x1, mem_k, mem_v):
        """One cross-attention step against precomputed memory K/V —
        the shared sdpa op, so the cached path cannot drift numerically
        from forward()."""
        import jax.numpy as jnp
        from ..tensor import Tensor as _T
        n = x1.shape[0]
        q = self.q_proj(x1).reshape([n, 1, self.h, self.dk]).transpose(
            [0, 2, 1, 3])
        ctx = F.scaled_dot_product_attention(
            q, _T(mem_k), _T(mem_v), dropout_p=0.0, training=False)
        return self.out(ctx.transpose([0, 2, 1, 3]).reshape([n, 1, -1]))


class EncoderLayer(nn.Layer):
    def __init__(self, d_model, num_heads, d_ff, dropout=0.1):
        super().__init__()
        self.self_attn = CrossAttention(d_model, num_heads, dropout)
        self.norm1 = nn.LayerNorm(d_model)
        self.norm2 = nn.LayerNorm(d_model)
        self.ffn1 = nn.Linear(d_model, d_ff)
        self.ffn2 = nn.Linear(d_ff, d_model)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        h = self.norm1(x)
        x = x + self.dropout(self.self_attn(h, h, mask))
        h = self.norm2(x)
        return x + self.dropout(self.ffn2(F.relu(self.ffn1(h))))


class DecoderLayer(nn.Layer):
    def __init__(self, d_model, num_heads, d_ff, dropout=0.1):
        super().__init__()
        self.self_attn = CrossAttention(d_model, num_heads, dropout)
        self.cross_attn = CrossAttention(d_model, num_heads, dropout)
        self.norm1 = nn.LayerNorm(d_model)
        self.norm2 = nn.LayerNorm(d_model)
        self.norm3 = nn.LayerNorm(d_model)
        self.ffn1 = nn.Linear(d_model, d_ff)
        self.ffn2 = nn.Linear(d_ff, d_model)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, memory, self_mask=None, cross_mask=None):
        h = self.norm1(x)
        x = x + self.dropout(self.self_attn(h, h, self_mask,
                                            is_causal=True))
        h = self.norm2(x)
        x = x + self.dropout(self.cross_attn(h, memory, cross_mask))
        h = self.norm3(x)
        return x + self.dropout(self.ffn2(F.relu(self.ffn1(h))))

    def forward_step(self, x1, mem_k, mem_v, ck, cv, pos):
        """Incremental decode step (eval mode, dropout off): same residual
        structure as forward over ONE new position with cached K/V."""
        h = self.norm1(x1)
        sa, ck, cv = self.self_attn.step_self(h, ck, cv, pos)
        x1 = x1 + sa
        h = self.norm2(x1)
        x1 = x1 + self.cross_attn.step_cross(h, mem_k, mem_v)
        h = self.norm3(x1)
        return x1 + self.ffn2(F.relu(self.ffn1(h))), ck, cv


class Transformer(nn.Layer):
    """Pre-norm Transformer (base: d=512,h=8,L=6,ff=2048; big: d=1024,h=16,
    ff=4096 — the reference benchmark config)."""

    def __init__(self, src_vocab_size=32000, tgt_vocab_size=32000,
                 d_model=512, num_heads=8, num_encoder_layers=6,
                 num_decoder_layers=6, d_ff=2048, dropout=0.1,
                 max_length=1024, label_smooth_eps=0.1,
                 weight_sharing=False):
        super().__init__()
        self.d_model = d_model
        self.label_smooth_eps = label_smooth_eps
        self.src_embed = nn.Embedding(src_vocab_size, d_model)
        self.tgt_embed = (self.src_embed if weight_sharing
                          else nn.Embedding(tgt_vocab_size, d_model))
        from ..tensor import Tensor
        self.register_buffer(
            "pos_enc", Tensor(sinusoid_position_encoding(max_length,
                                                         d_model)))
        self.encoder = nn.LayerList([
            EncoderLayer(d_model, num_heads, d_ff, dropout)
            for _ in range(num_encoder_layers)])
        self.enc_norm = nn.LayerNorm(d_model)
        self.decoder = nn.LayerList([
            DecoderLayer(d_model, num_heads, d_ff, dropout)
            for _ in range(num_decoder_layers)])
        self.dec_norm = nn.LayerNorm(d_model)
        self.out_proj = nn.Linear(d_model, tgt_vocab_size)
        self.dropout = nn.Dropout(dropout)
        self.scale = float(np.sqrt(d_model))

    def _embed(self, table, ids):
        s = ids.shape[1]
        x = table(ids) * self.scale + self.pos_enc[:s]
        return self.dropout(x)

    def encode(self, src_ids, src_mask=None):
        x = self._embed(self.src_embed, src_ids)
        for layer in self.encoder:
            x = layer(x, src_mask)
        return self.enc_norm(x)

    def decode(self, tgt_ids, memory, cross_mask=None):
        x = self._embed(self.tgt_embed, tgt_ids)
        for layer in self.decoder:
            x = layer(x, memory, cross_mask=cross_mask)
        return self.out_proj(self.dec_norm(x))

    def init_cache(self, n, t_max):
        """Per-decoder-layer raw [N, H, T_max, dk] self-attention K/V
        caches (the beam search reorders these by parent beam each
        step)."""
        import jax.numpy as jnp
        h = self.decoder[0].self_attn.h
        dk = self.decoder[0].self_attn.dk
        return tuple(
            (jnp.zeros((n, h, t_max, dk), jnp.float32),
             jnp.zeros((n, h, t_max, dk), jnp.float32))
            for _ in range(len(self.decoder)))

    def decode_step(self, tokens, pos, caches, mem_kv):
        """One incremental decode position: tokens [N, 1] -> logits
        [N, V], with all self-attention K/V cached (O(T) per step instead
        of the O(T^2) full-prefix re-decode). pos: traced scalar."""
        import jax
        from ..tensor import Tensor as _T
        emb = self.tgt_embed(tokens) * self.scale
        pe = jax.lax.dynamic_index_in_dim(self.pos_enc.data, pos, axis=0,
                                          keepdims=True)
        x = _T(emb.data + pe[None])
        new_caches = []
        for layer, (ck, cv), (mk, mv) in zip(self.decoder, caches, mem_kv):
            x, ck, cv = layer.forward_step(x, mk, mv, ck, cv, pos)
            new_caches.append((ck, cv))
        return self.out_proj(self.dec_norm(x)), tuple(new_caches)

    def forward(self, src_ids, tgt_ids, src_mask=None):
        cross_mask = None
        if src_mask is not None:
            cross_mask = ((1.0 - src_mask.astype("float32")) * -1e9
                          ).unsqueeze(1).unsqueeze(1)
        memory = self.encode(src_ids, cross_mask)
        return self.decode(tgt_ids, memory, cross_mask)

    def generate(self, src_ids, beam_size=4, max_len=32, bos_id=1,
                 eos_id=2, use_cache=True):
        """Beam-search translation (reference: the WMT book config decodes
        with fluid BeamSearchDecoder/dynamic_decode, layers/rnn.py:687).

        TPU formulation: beam bookkeeping runs in nn.decode's
        lax.while_loop over static shapes. With use_cache (default) each
        step runs the O(T) incremental decoder over per-layer K/V caches
        (the beam search gathers the caches by parent beam); the
        use_cache=False path re-decodes the full prefix per step and
        exists as the parity oracle.

        Returns (ids [B, T, K], scores [B, K])."""
        import jax
        import jax.numpy as jnp
        from ..nn.decode import BeamSearchDecoder, dynamic_decode
        from ..tensor import Tensor

        was_training = self.training
        self.eval()
        try:
            if int(max_len) > int(self.pos_enc.shape[0]):
                raise ValueError(
                    f"max_len={max_len} exceeds the model's max_length="
                    f"{self.pos_enc.shape[0]} positional table")
            memory = self.encode(src_ids)
            mem = BeamSearchDecoder.tile_beam_merge_with_batch(memory,
                                                               beam_size)
            b = src_ids.shape[0]
            t_max = int(max_len)
            n = b * beam_size
            model = self

            if use_cache:
                # project cross K/V from the UNTILED memory (one matmul
                # per source row), then tile per beam
                def _tile(a):
                    return jnp.repeat(a, beam_size, axis=0)
                mem_kv = tuple(
                    tuple(_tile(a) for a in
                          layer.cross_attn.precompute_kv(memory))
                    for layer in self.decoder)

                class _CachedCell:
                    def __call__(self, tokens, states):
                        caches, t = states
                        pos = t.data.reshape(-1)[0]
                        logits, new_caches = model.decode_step(
                            Tensor(tokens.data.reshape(-1, 1)
                                   .astype(jnp.int32)),
                            pos, caches, mem_kv)
                        out = logits.data[:, 0]
                        return Tensor(out), (new_caches,
                                             Tensor(t.data + 1))

                cell = _CachedCell()
                # [B, ...] here — BeamSearchDecoder.initialize tiles every
                # state leaf to [B*beam, ...]
                init = (model.init_cache(b, t_max),
                        Tensor(jnp.zeros((b, 1), jnp.int32)))
            else:
                class _PrefixCell:
                    def __call__(self, tokens, states):
                        buf, t = states
                        tcur = t.data.reshape(-1)[0]
                        buf_arr = buf.data.at[:, tcur].set(
                            tokens.data.reshape(-1).astype(jnp.int32))
                        logits = model.decode(Tensor(buf_arr), mem)
                        out = jax.lax.dynamic_index_in_dim(
                            logits.data, tcur, axis=1, keepdims=False)
                        return Tensor(out), (Tensor(buf_arr),
                                             Tensor(t.data + 1))

                cell = _PrefixCell()
                init = (Tensor(jnp.full((b, t_max), eos_id, jnp.int32)),
                        Tensor(jnp.zeros((b, 1), jnp.int32)))

            decoder = BeamSearchDecoder(cell, bos_id, eos_id, beam_size)
            ids, scores = dynamic_decode(decoder, init,
                                         max_step_num=t_max)
            return ids, scores
        finally:
            if was_training:
                self.train()

    def loss(self, logits, labels, pad_id=0):
        """Label-smoothed CE averaged over non-pad tokens (reference:
        label_smooth + softmax_with_cross_entropy(soft_label=True)). On
        TPU the smoothing folds into the fused Pallas xent kernel, so the
        (B, S, V) smoothed one-hot never materializes in HBM."""
        from ..ops import pallas as P
        vocab = logits.shape[-1]
        if P.enabled("softmax_xent"):
            token_loss = P.softmax_cross_entropy(
                logits, labels, smooth_eps=self.label_smooth_eps)
        else:
            soft = F.label_smooth(ops.one_hot(labels, vocab),
                                  epsilon=self.label_smooth_eps)
            token_loss = ops.loss.softmax_with_cross_entropy(
                logits, soft, soft_label=True)
        mask = (labels != pad_id).astype("float32").unsqueeze(-1)
        return (token_loss * mask).sum() / mask.sum()
