"""paddle_tpu.regularizer — weight decay regularizers.

TPU-native rebuild of reference python/paddle/fluid/regularizer.py
(L1DecayRegularizer, L2DecayRegularizer): applied by adding the penalty
gradient to the parameter gradient inside the (compiled) update step.
"""
from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def grad_term(self, param):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    """reference: L2DecayRegularizer — grad += coeff * param."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, param):
        return self.coeff * param

    def __float__(self):
        return self.coeff


class L1Decay(WeightDecayRegularizer):
    """reference: L1DecayRegularizer — grad += coeff * sign(param)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, param):
        return self.coeff * jnp.sign(param)


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay
