"""paddle_tpu.metric — evaluation metrics.

TPU-native rebuild of reference python/paddle/fluid/metrics.py
(MetricBase, Accuracy, Precision, Recall, Auc, CompositeMetric,
ChunkEvaluator, EditDistance) + layers.accuracy/auc. Device work (argmax,
comparisons) runs as jax ops; scalar accumulation is host-side numpy, like
the reference's numpy accumulators.
"""
from __future__ import annotations

import numpy as np
import jax

from .tensor import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(jax.device_get(x.data))
    return np.asarray(x)


class Metric:
    """Base (reference: metrics.py:MetricBase)."""

    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    # fluid-era alias
    def eval(self):
        return self.accumulate()


MetricBase = Metric


class Accuracy(Metric):
    """reference: metrics.py:Accuracy (+ layers.accuracy top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name)
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        """Returns per-sample correctness for each k."""
        pred = _np(pred)
        label = _np(label).reshape(-1)
        maxk = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :maxk]
        correct = top == label[:, None]
        return correct

    def update(self, correct_or_pred, label=None):
        if label is not None:
            correct = self.compute(correct_or_pred, label)
        else:
            correct = _np(correct_or_pred)
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(axis=-1).sum()
            self.count[i] += correct.shape[0]
        return self.total / np.maximum(self.count, 1)

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else acc


class Precision(Metric):
    """reference: metrics.py:Precision (binary)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    """reference: metrics.py:Recall."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    """reference: metrics.py:Auc — histogram-bucketed ROC AUC (matches the
    reference's stat_pos/stat_neg accumulator design)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # P(score_pos > score_neg) + 0.5 P(tie): ascending buckets, count
        # negatives strictly below + half of same-bucket ties
        area = 0.0
        cum_neg = 0.0
        for p, n in zip(self._stat_pos, self._stat_neg):
            area += p * (cum_neg + n / 2.0)
            cum_neg += n
        return float(area / (tot_pos * tot_neg))


class CompositeMetric(Metric):
    """reference: metrics.py:CompositeMetric."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def accumulate(self):
        return [m.accumulate() for m in self._metrics]


class ChunkEvaluator(Metric):
    """reference: metrics.py:ChunkEvaluator — sequence chunk F1 from
    (num_infer_chunks, num_label_chunks, num_correct_chunks) counts."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += int(_np(num_infer_chunks).sum())
        self.num_label += int(_np(num_label_chunks).sum())
        self.num_correct += int(_np(num_correct_chunks).sum())

    def accumulate(self):
        precision = self.num_correct / self.num_infer if self.num_infer else 0
        recall = self.num_correct / self.num_label if self.num_label else 0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class EditDistance(Metric):
    """reference: metrics.py:EditDistance (normalized levenshtein)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    @staticmethod
    def _levenshtein(a, b):
        m, n = len(a), len(b)
        dp = np.arange(n + 1, dtype=np.int64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                cost = 0 if a[i - 1] == b[j - 1] else 1
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1, prev[j - 1] + cost)
        return int(dp[n])

    def update(self, hyps, refs):
        for h, r in zip(hyps, refs):
            h = list(_np(h).reshape(-1)) if not isinstance(h, str) else h
            r = list(_np(r).reshape(-1)) if not isinstance(r, str) else r
            d = self._levenshtein(h, r)
            norm = d / max(len(r), 1)
            self.total_distance += norm
            self.seq_num += 1
            if d > 0:
                self.instance_error += 1

    def accumulate(self):
        if not self.seq_num:
            return 0.0, 0.0
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


# functional surface (reference: layers.accuracy / layers.auc)
def accuracy(input, label, k=1):
    pred = _np(input)
    label = _np(label).reshape(-1)
    top = np.argsort(-pred, axis=-1)[..., :k]
    correct = (top == label[:, None]).any(axis=-1)
    return Tensor(np.asarray(correct.mean(), np.float32))


class DetectionMAP(Metric):
    """reference: metrics.py:DetectionMAP — accumulating detection mAP.
    update() banks per-image detections/labels; accumulate() computes ONE
    global-dataset mAP over everything banked (matching the reference's
    threaded pos_count/true_pos/false_pos accumulation)."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral",
                 name=None):
        super().__init__(name)
        self.class_num = class_num
        self.background_label = background_label
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []
        self._labs = []

    def update(self, detect_res, label):
        det = np.asarray(jax.device_get(
            detect_res.data if isinstance(detect_res, Tensor)
            else detect_res))
        lab = np.asarray(jax.device_get(
            label.data if isinstance(label, Tensor) else label))
        if det.ndim == 2:
            det, lab = det[None], lab[None]
        self._dets.extend(list(det))
        self._labs.extend(list(lab))
        return None  # bank only; mAP computed once in accumulate()

    def accumulate(self):
        from .fluid.layers_extra2 import _map_eval
        return _map_eval(self._dets, self._labs, self.class_num,
                         self.background_label, self.overlap_threshold,
                         self.evaluate_difficult, self.ap_version)

    get_map_var = update
    cur_map = accumulate
