"""Light-NAS (reference: contrib/slim/nas/light_nas_strategy.py:1,
search_space.py, controller_server.py).

Reasoned facade: the reference's LightNAS is a simulated-annealing
architecture search driven by a socket controller server coordinating
multiple trainer processes — a CPU-side search harness, not an
accelerator workload. The TPU rebuild keeps the SearchSpace contract (so
user search spaces port unchanged) and a single-process annealing driver;
the distributed controller-server machinery is intentionally out of scope
(multi-host search coordination belongs to the cluster layer, not the
framework)."""
from __future__ import annotations

import math
import random

__all__ = ["SearchSpace", "LightNASStrategy"]


class SearchSpace:
    """reference: search_space.py:20 — user subclasses implement these."""

    def init_tokens(self):
        """Initial token vector encoding an architecture."""
        raise NotImplementedError

    def range_table(self):
        """Per-token upper bounds (list of ints)."""
        raise NotImplementedError

    def create_model(self, tokens=None):
        """Build the model for a token vector."""
        raise NotImplementedError


class LightNASStrategy:
    """Single-process simulated-annealing search over a SearchSpace
    (reference: light_nas_strategy.py + controller.py SAController).

    eval_fn(model) -> reward (higher better). Distributed
    controller-server search is deliberately not implemented — see module
    docstring."""

    def __init__(self, search_space, eval_fn, init_temperature=100.0,
                 reduce_rate=0.85, search_steps=10, seed=0):
        self.space = search_space
        self.eval_fn = eval_fn
        self.t = init_temperature
        self.reduce_rate = reduce_rate
        self.search_steps = search_steps
        self._rng = random.Random(seed)

    def _mutate(self, tokens, table):
        tokens = list(tokens)
        i = self._rng.randrange(len(tokens))
        tokens[i] = self._rng.randrange(table[i])
        return tokens

    def search(self):
        """Returns (best_tokens, best_reward, history)."""
        table = self.space.range_table()
        cur = list(self.space.init_tokens())
        cur_r = self.eval_fn(self.space.create_model(cur))
        best, best_r = cur, cur_r
        history = [(list(cur), cur_r)]
        for _ in range(self.search_steps):
            cand = self._mutate(cur, table)
            r = self.eval_fn(self.space.create_model(cand))
            history.append((list(cand), r))
            if r > cur_r or self._rng.random() < math.exp(
                    (r - cur_r) / max(self.t, 1e-9)):
                cur, cur_r = cand, r
            if r > best_r:
                best, best_r = cand, r
            self.t *= self.reduce_rate
        return best, best_r, history
