"""Pruning (reference: contrib/slim/prune/pruner.py:1 Pruner /
StructurePruner, prune_strategy.py:1 sensitive/uniform strategies).

Two layers of API:

* ``StructurePruner`` keeps the reference's numpy-level contract
  (``cal_pruned_idx`` / ``prune_tensor`` with l1_norm group criterion);
* :func:`prune_model` is the dygraph transform: it computes PERSISTENT
  0/1 masks for the chosen parameters (magnitude / structured l1-norm)
  and registers them as buffers; every masked parameter is multiplied by
  its mask on the forward path (a forward-pre hook swaps the masked value
  in), so pruned weights contribute nothing to forward OR gradient and
  stay pruned through finetuning — the state_dict still holds dense
  arrays + masks, which is what a TPU wants (dense MXU math; the zeros
  compress at serialization time).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from ..nn.layer import Layer

__all__ = ["Pruner", "StructurePruner", "MagnitudePruner", "prune_model",
           "sensitivity"]


class Pruner:
    """reference: pruner.py:22 — base class."""

    def prune(self, param, ratio):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Group (filter/channel) pruning by axis (reference: pruner.py:34).

    pruning_axis: {param_name_or_'*': axis}; criterions:
    {param_name_or_'*': 'l1_norm'}."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices of the lowest-norm groups along `axis`."""
        criterion = self.criterions.get(name, self.criterions.get("*"))
        if criterion != "l1_norm":
            raise ValueError(f"unsupported criterion {criterion!r}")
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*"))
        param = np.asarray(param)
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        scores = np.sum(np.abs(param), axis=reduce_dims)
        return np.argsort(scores)[:prune_num]

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        """Drop (or zero, when lazy) the pruned groups."""
        tensor = np.asarray(tensor)
        if lazy:
            out = tensor.copy()
            idx = [slice(None)] * tensor.ndim
            idx[pruned_axis] = pruned_idx
            out[tuple(idx)] = 0
            return out
        keep = np.setdiff1d(np.arange(tensor.shape[pruned_axis]),
                            pruned_idx)
        return np.take(tensor, keep, axis=pruned_axis)

    def mask(self, name, param, ratio, axis=None):
        """0/1 mask zeroing the pruned groups (persistent-mask form)."""
        param = np.asarray(param)
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*"))
        idx = self.cal_pruned_idx(name, param, ratio, axis)
        m = np.ones(param.shape, "float32")
        sl = [slice(None)] * param.ndim
        sl[axis] = idx
        m[tuple(sl)] = 0.0
        return m


class MagnitudePruner(Pruner):
    """Unstructured magnitude pruning: zero the smallest |w| entries."""

    def mask(self, name, param, ratio, axis=None):
        param = np.asarray(param)
        k = int(round(param.size * ratio))
        if k <= 0:
            return np.ones(param.shape, "float32")
        thresh = np.partition(np.abs(param).ravel(), k - 1)[k - 1]
        return (np.abs(param) > thresh).astype("float32")

    def prune(self, param, ratio):
        return np.asarray(param) * self.mask("", param, ratio)


def _iter_target_params(model, params=None):
    for name, p in model.named_parameters():
        if params is not None and not any(pat in name for pat in params):
            continue
        if p.data.ndim < 2:  # biases/norms are never pruned
            continue
        yield name, p


def prune_model(model, ratios, pruner=None, params=None):
    """Apply persistent pruning masks to `model` in place.

    ratios: float (uniform) or {param_substring: ratio}. pruner: a
    Pruner with .mask() (default MagnitudePruner). params: optional list
    of name substrings to restrict pruning. Returns {name: mask}.

    The masks install as forward-pre hooks on each owning layer: the
    parameter's value is multiplied by its mask for the call and restored
    after, so optimizer state keeps tracking the dense parameter while
    pruned weights stay exactly zero in every forward/backward
    (reference: prune_strategy.py applying pruned params on the graph).
    """
    pruner = pruner or MagnitudePruner()
    if not isinstance(ratios, dict):
        ratios = {"": float(ratios)}
    masks = {}
    # name -> (owning layer, attr) map for hook installation
    owners = {}
    for lname, layer in model.named_sublayers(include_self=True):
        for attr, p in layer._parameters.items():
            full = f"{lname}.{attr}" if lname else attr
            owners[full] = (layer, attr)

    for name, p in _iter_target_params(model, params):
        ratio = None
        for pat, r in ratios.items():
            if pat in name:
                ratio = r
                break
        if ratio is None or ratio <= 0:
            continue
        m = pruner.mask(name, np.asarray(p.data), ratio)
        mask_arr = jnp.asarray(m)
        p.data = p.data * mask_arr  # prune NOW
        masks[name] = mask_arr
        layer, attr = owners[name]

        def make_hook(attr, mask_arr):
            state = {}

            def pre(layer_, inputs):
                param = layer_._parameters[attr]
                state["dense"] = param.data
                param.data = param.data * mask_arr
                return None

            def post(layer_, inputs, outputs):
                # restore the dense value so the optimizer updates it;
                # the NEXT forward re-masks (masked-forward => masked
                # grads, so pruned entries only drift by weight decay and
                # are re-zeroed each call)
                layer_._parameters[attr].data = state.pop("dense")
                return None

            return pre, post

        pre, post = make_hook(attr, mask_arr)
        layer.register_forward_pre_hook(pre)
        layer.register_forward_post_hook(post)
        if not hasattr(layer, "_prune_masks"):
            layer._prune_masks = {}
        layer._prune_masks[attr] = mask_arr
    return masks


def sensitivity(model, eval_fn, ratios=(0.1, 0.3, 0.5), pruner=None,
                params=None):
    """Per-parameter pruning sensitivity (reference:
    prune_strategy.py SensitivePruneStrategy): for each prunable param,
    temporarily prune at each ratio and record eval_fn(model). Returns
    {param_name: {ratio: metric}}; the model is restored afterwards."""
    pruner = pruner or MagnitudePruner()
    out = {}
    for name, p in _iter_target_params(model, params):
        dense = p.data
        scores = {}
        for r in ratios:
            m = pruner.mask(name, np.asarray(dense), r)
            p.data = dense * jnp.asarray(m)
            scores[float(r)] = float(eval_fn(model))
        p.data = dense
        out[name] = scores
    return out
