"""Knowledge distillation (reference: contrib/slim/distillation/
distiller.py:1 — L2Distiller, FSPDistiller, SoftLabelDistiller — and
distillation_strategy.py merging teacher+student graphs).

The reference merges the teacher Program into the student's and wires
loss ops between named vars. The dygraph redesign: a
``DistillationModel`` wrapper runs teacher (no-grad) and student on the
same input, captures intermediate features by LAYER NAME via forward-post
hooks, and builds the combined distillation loss from declarative specs —
the same (s_name, t_name) pairing language the reference uses, minus the
graph surgery.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..ops import nn_ops as F
from ..nn.layer import Layer
from .. import autograd as _ag

__all__ = ["l2_distill", "soft_label_distill", "fsp_matrix",
           "fsp_distill", "merge", "DistillationModel"]


def l2_distill(teacher_feat, student_feat, weight=1.0):
    """reference distiller.py:25 L2Distiller: mean-square feature match."""
    return (student_feat - teacher_feat).square().mean() * weight


def soft_label_distill(teacher_logits, student_logits,
                       teacher_temperature=2.0, student_temperature=2.0,
                       weight=1.0):
    """reference distiller.py:195 SoftLabelDistiller:
    CE(softmax(t/Tt), log_softmax(s/Ts))."""
    t = F.softmax(teacher_logits / teacher_temperature)
    s = F.log_softmax(student_logits / student_temperature)
    return -(t * s).sum(axis=-1).mean() * weight


def fsp_matrix(feat_a, feat_b):
    """reference distiller.py:191 _fsp_matrix: the FSP (flow of solution
    procedure) gram matrix between two NCHW feature maps of equal spatial
    size: [N, Ca, Cb] = A·Bᵀ over the flattened spatial axis / (H*W)."""
    n, ca, h, w = feat_a.shape
    cb = feat_b.shape[1]
    a = feat_a.reshape([n, ca, h * w])
    b = feat_b.reshape([n, cb, h * w]).transpose([0, 2, 1])
    return ops.matmul(a, b) / float(h * w)


def fsp_distill(teacher_pair, student_pair, weight=1.0):
    """reference distiller.py:103 FSPDistiller: L2 between teacher and
    student FSP matrices of a (start, end) feature-map pair."""
    tm = fsp_matrix(*teacher_pair)
    sm = fsp_matrix(*student_pair)
    return (sm - tm).square().mean() * weight


def merge(teacher, student, *args, **kwargs):
    """reference distillation_strategy.py graph merge — in the dygraph
    redesign teacher/student stay separate Layers; use
    DistillationModel."""
    return DistillationModel(student, teacher)


class DistillationModel(Layer):
    """Wraps (student, teacher) for distillation training.

    distill_specs: list of dicts —
      {"kind": "soft_label", "s": s_layer_name, "t": t_layer_name,
       "weight": w, "teacher_temperature": Tt, "student_temperature": Ts}
      {"kind": "l2", "s": ..., "t": ..., "weight": w}
      {"kind": "fsp", "s": (name_a, name_b), "t": (name_a, name_b),
       "weight": w}
    Layer names are as in named_sublayers(); captured feature = that
    layer's forward OUTPUT. Calling the wrapper returns (student_out,
    distill_loss); add your task loss to distill_loss and train — only
    student parameters receive gradients (teacher runs under no_grad).
    """

    def __init__(self, student, teacher, distill_specs=None):
        super().__init__()
        self.student = student
        # teacher is intentionally NOT registered as a sublayer: its
        # params must not reach the optimizer / state_dict of the
        # distilled model
        object.__setattr__(self, "teacher", teacher)
        self.specs = distill_specs or []
        self._s_feats = {}
        self._t_feats = {}
        self._hook_names = self._needed_names()
        self._install_hooks()

    def _needed_names(self):
        s_names, t_names = set(), set()
        for spec in self.specs:
            s, t = spec.get("s"), spec.get("t")
            for names, v in ((s_names, s), (t_names, t)):
                if isinstance(v, (tuple, list)):
                    names.update(v)
                elif v is not None:
                    names.add(v)
        return {"s": s_names, "t": t_names}

    def _install_hooks(self):
        def cap(store, name):
            def hook(layer, inputs, output):
                store[name] = output
                return None
            return hook

        for name, sub in self.student.named_sublayers(include_self=True):
            if name in self._hook_names["s"]:
                sub.register_forward_post_hook(cap(self._s_feats, name))
        for name, sub in self.teacher.named_sublayers(include_self=True):
            if name in self._hook_names["t"]:
                sub.register_forward_post_hook(cap(self._t_feats, name))

    def _feat(self, store, key):
        if isinstance(key, (tuple, list)):
            return tuple(store[k] for k in key)
        return store[key]

    def forward(self, *args, **kwargs):
        self.teacher.eval()
        with _ag.no_grad():
            t_out = self.teacher(*args, **kwargs)
        s_out = self.student(*args, **kwargs)
        loss = None
        for spec in self.specs:
            kind = spec["kind"]
            w = spec.get("weight", 1.0)
            if kind == "soft_label":
                t = self._feat(self._t_feats, spec["t"]) \
                    if spec.get("t") else t_out
                s = self._feat(self._s_feats, spec["s"]) \
                    if spec.get("s") else s_out
                term = soft_label_distill(
                    t, s, spec.get("teacher_temperature", 2.0),
                    spec.get("student_temperature", 2.0), w)
            elif kind == "l2":
                term = l2_distill(self._feat(self._t_feats, spec["t"]),
                                  self._feat(self._s_feats, spec["s"]), w)
            elif kind == "fsp":
                term = fsp_distill(self._feat(self._t_feats, spec["t"]),
                                   self._feat(self._s_feats, spec["s"]), w)
            else:
                raise ValueError(f"unknown distill kind {kind!r}")
            loss = term if loss is None else loss + term
        self._s_feats.clear()
        self._t_feats.clear()
        if loss is None:
            loss = soft_label_distill(t_out, s_out)
        return s_out, loss
