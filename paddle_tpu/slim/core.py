"""Compressor driver (reference: contrib/slim/core/compressor.py:1 —
604 L epoch loop dispatching on_epoch/on_batch callbacks into the
registered strategies, with checkpoint/eval plumbing).

Dygraph redesign: strategies are small objects with on_compression_begin
/ on_epoch_begin / on_epoch_end hooks receiving a Context; the Compressor
runs the train loop (any callable train_fn(model, batch) -> loss works —
typically a jit.to_static step) and applies strategies at their scheduled
epochs."""
from __future__ import annotations

import numpy as np

__all__ = ["Context", "Strategy", "PruneStrategy",
           "DistillationStrategy", "Compressor"]


class Context:
    """What strategies see (reference: compressor.py Context)."""

    def __init__(self, model, optimizer=None, epoch=0):
        self.model = model
        self.optimizer = optimizer
        self.epoch = epoch
        self.eval_results = {}


class Strategy:
    """reference: strategy.py:17 — epoch-windowed callbacks."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass


class PruneStrategy(Strategy):
    """Uniform magnitude/structured pruning at start_epoch (reference:
    prune_strategy.py UniformPruneStrategy). The masks persist through
    subsequent finetuning epochs."""

    def __init__(self, ratios, pruner=None, params=None, start_epoch=0,
                 end_epoch=0):
        super().__init__(start_epoch, end_epoch)
        self.ratios = ratios
        self.pruner = pruner
        self.params = params
        self.masks = None

    def on_epoch_begin(self, context):
        from .prune import prune_model
        if context.epoch == self.start_epoch and self.masks is None:
            self.masks = prune_model(context.model, self.ratios,
                                     pruner=self.pruner,
                                     params=self.params)


class DistillationStrategy(Strategy):
    """Swap the model for a DistillationModel during [start, end) epochs
    (reference: distillation_strategy.py)."""

    def __init__(self, teacher, distill_specs=None, start_epoch=0,
                 end_epoch=1000):
        super().__init__(start_epoch, end_epoch)
        self.teacher = teacher
        self.specs = distill_specs

    def on_compression_begin(self, context):
        from .distill import DistillationModel
        context.model = DistillationModel(context.model, self.teacher,
                                          self.specs)


class Compressor:
    """reference: compressor.py:64 — the strategy-driven train loop.

    train_fn(model, batch) -> loss float; eval_fn(model) -> metric.
    train_reader: callable returning an iterable of batches per epoch.
    """

    def __init__(self, model, optimizer=None, train_fn=None,
                 train_reader=None, eval_fn=None, epochs=1, strategies=()):
        self.context = Context(model, optimizer)
        self.train_fn = train_fn
        self.train_reader = train_reader
        self.eval_fn = eval_fn
        self.epochs = epochs
        self.strategies = list(strategies)

    def run(self):
        ctx = self.context
        for s in self.strategies:
            s.on_compression_begin(ctx)
        history = []
        for epoch in range(self.epochs):
            ctx.epoch = epoch
            for s in self.strategies:
                s.on_epoch_begin(ctx)
            losses = []
            if self.train_fn and self.train_reader:
                for batch in self.train_reader():
                    losses.append(float(self.train_fn(ctx.model, batch)))
            for s in self.strategies:
                s.on_epoch_end(ctx)
            metric = float(self.eval_fn(ctx.model)) if self.eval_fn \
                else None
            ctx.eval_results[epoch] = metric
            history.append({"epoch": epoch,
                            "loss": float(np.mean(losses)) if losses
                            else None,
                            "metric": metric})
        for s in self.strategies:
            s.on_compression_end(ctx)
        return ctx.model, history
