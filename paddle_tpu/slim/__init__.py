"""paddle_tpu.slim — model compression: pruning, distillation, NAS, and
the Compressor driver (quantization lives in paddle_tpu.quantization).

TPU-native rebuild of the reference's slim suite
(reference: python/paddle/fluid/contrib/slim/{prune,distillation,nas,core}).
The reference's strategies rewrite the static Program graph between
epochs; here each strategy is a dygraph Layer transform / loss builder,
which composes with jit.to_static and GSPMD sharding the same way the
rest of the framework does.
"""
from .prune import (Pruner, StructurePruner, MagnitudePruner,
                    prune_model, sensitivity)
from .distill import (l2_distill, soft_label_distill, fsp_matrix,
                      fsp_distill, DistillationModel, merge)
from .nas import SearchSpace, LightNASStrategy
from .core import Compressor, Strategy, PruneStrategy, DistillationStrategy

__all__ = [
    "Pruner", "StructurePruner", "MagnitudePruner", "prune_model",
    "sensitivity", "l2_distill", "soft_label_distill", "fsp_matrix",
    "fsp_distill", "DistillationModel", "merge", "SearchSpace",
    "LightNASStrategy", "Compressor", "Strategy", "PruneStrategy",
    "DistillationStrategy",
]
