"""paddle_tpu.distribution — probability distributions.

TPU-native rebuild of the reference's distributions
(reference: python/paddle/fluid/layers/distributions.py — Uniform:115,
Normal:260, Categorical:424, MultivariateNormalDiag:530). The reference
builds sampling from uniform_random/gaussian_random graph ops with
stateful seeds; here sampling draws threaded PRNG subkeys from the global
key (paddle_tpu.random), so samples are reproducible under `paddle.seed`
and jit-safe (the key is an explicit input, the XLA requirement).

All math (log_prob / entropy / kl_divergence) is pure jax dispatched
through `apply`, so it differentiates through the tape and records into
static Programs like any other op.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply
from .tensor import Tensor, as_tensor
from . import random as prandom

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag"]


def _as_float_tensor(x):
    if isinstance(x, Tensor):
        return x
    arr = np.asarray(x, dtype="float32")
    return as_tensor(arr)


class Distribution:
    """Abstract base (reference distributions.py:30)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def _key(self, seed):
        if seed:
            return jax.random.PRNGKey(int(seed))
        return prandom.next_key()


class Uniform(Distribution):
    """U(low, high) (reference distributions.py:115): broadcastable low /
    high; sample, log_prob, entropy."""

    def __init__(self, low, high):
        self.low = _as_float_tensor(low)
        self.high = _as_float_tensor(high)

    def sample(self, shape, seed=0):
        key = self._key(seed)
        shape = tuple(shape)

        def impl(low, high, key):
            bshape = shape + jnp.broadcast_shapes(low.shape, high.shape)
            u = jax.random.uniform(key, bshape, jnp.float32)
            return low + (high - low) * u

        return apply(impl, (self.low, self.high, key), nondiff=True,
                     name="uniform_sample")

    def log_prob(self, value):
        def impl(low, high, v):
            inside = (v > low) & (v < high)
            lp = -jnp.log(high - low)
            return jnp.where(inside, lp, -jnp.inf)

        return apply(impl, (self.low, self.high, value),
                     name="uniform_log_prob")

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo),
                     (self.low, self.high), name="uniform_entropy")


class Normal(Distribution):
    """N(loc, scale) (reference distributions.py:260): sample, entropy,
    log_prob, kl_divergence."""

    def __init__(self, loc, scale):
        self.loc = _as_float_tensor(loc)
        self.scale = _as_float_tensor(scale)

    def sample(self, shape, seed=0):
        key = self._key(seed)
        shape = tuple(shape)

        def impl(loc, scale, key):
            bshape = shape + jnp.broadcast_shapes(loc.shape, scale.shape)
            eps = jax.random.normal(key, bshape, jnp.float32)
            return loc + scale * eps

        return apply(impl, (self.loc, self.scale, key), nondiff=True,
                     name="normal_sample")

    def entropy(self):
        def impl(loc, scale):
            scale = jnp.broadcast_to(scale,
                                     jnp.broadcast_shapes(loc.shape,
                                                          scale.shape))
            return 0.5 + 0.5 * math.log(2.0 * math.pi) + jnp.log(scale)

        return apply(impl, (self.loc, self.scale), name="normal_entropy")

    def log_prob(self, value):
        def impl(loc, scale, v):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var) - jnp.log(scale) -
                    0.5 * math.log(2.0 * math.pi))

        return apply(impl, (self.loc, self.scale, value),
                     name="normal_log_prob")

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence requires another Normal")

        def impl(l1, s1, l2, s2):
            ratio = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (ratio + t1 - 1.0 - jnp.log(ratio))

        return apply(impl, (self.loc, self.scale, other.loc, other.scale),
                     name="normal_kl")


class Categorical(Distribution):
    """Categorical over logits (reference distributions.py:424): sample,
    entropy, kl_divergence, log_prob over the normalized probs."""

    def __init__(self, logits):
        self.logits = _as_float_tensor(logits)

    def sample(self, shape, seed=0):
        key = self._key(seed)
        shape = tuple(shape)

        def impl(logits, key):
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=shape + logits.shape[:-1])

        return apply(impl, (self.logits, key), nondiff=True,
                     name="categorical_sample")

    def entropy(self):
        def impl(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

        return apply(impl, (self.logits,), name="categorical_entropy")

    def log_prob(self, value):
        def impl(logits, v):
            logp = jax.nn.log_softmax(logits, axis=-1)
            if logp.ndim == 1:
                return logp[v.astype(jnp.int32)]
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), axis=-1)[..., 0]

        return apply(impl, (self.logits, value), name="categorical_log_prob")

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence requires another Categorical")

        def impl(a, b):
            pa = jax.nn.log_softmax(a, axis=-1)
            pb = jax.nn.log_softmax(b, axis=-1)
            return jnp.sum(jnp.exp(pa) * (pa - pb), axis=-1)

        return apply(impl, (self.logits, other.logits),
                     name="categorical_kl")


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference
    distributions.py:530). `scale` is the diagonal (batch, k) like the
    reference's diagonal-matrix formulation, but stored dense-free — all
    determinant/inverse math reduces to products over the diagonal."""

    def __init__(self, loc, scale):
        self.loc = _as_float_tensor(loc)
        self.scale = _as_float_tensor(scale)  # diagonal entries

    def _diag(self, scale):
        # accept (k,), (k, k) (reference passes a diagonal matrix)
        if scale.ndim >= 2 and scale.shape[-1] == scale.shape[-2]:
            return jnp.diagonal(scale, axis1=-2, axis2=-1)
        return scale

    def sample(self, shape, seed=0):
        key = self._key(seed)
        shape = tuple(shape)

        def impl(loc, scale, key):
            diag = self._diag(scale)
            bshape = shape + jnp.broadcast_shapes(loc.shape, diag.shape)
            eps = jax.random.normal(key, bshape, jnp.float32)
            return loc + diag * eps

        return apply(impl, (self.loc, self.scale, key), nondiff=True,
                     name="mvn_diag_sample")

    def entropy(self):
        def impl(loc, scale):
            diag = self._diag(scale)
            k = diag.shape[-1]
            return (0.5 * k * (1.0 + math.log(2.0 * math.pi)) +
                    jnp.sum(jnp.log(diag), axis=-1))

        return apply(impl, (self.loc, self.scale), name="mvn_diag_entropy")

    def log_prob(self, value):
        def impl(loc, scale, v):
            diag = self._diag(scale)
            k = diag.shape[-1]
            z = (v - loc) / diag
            return (-0.5 * jnp.sum(z * z, axis=-1) -
                    jnp.sum(jnp.log(diag), axis=-1) -
                    0.5 * k * math.log(2.0 * math.pi))

        return apply(impl, (self.loc, self.scale, value),
                     name="mvn_diag_log_prob")

    def kl_divergence(self, other):
        if not isinstance(other, MultivariateNormalDiag):
            raise TypeError("kl_divergence requires MultivariateNormalDiag")

        def impl(l1, s1, l2, s2):
            d1 = self._diag(s1)
            d2 = self._diag(s2)
            k = d1.shape[-1]
            ratio = (d1 / d2) ** 2
            t1 = ((l2 - l1) / d2) ** 2
            return 0.5 * (jnp.sum(ratio, axis=-1) + jnp.sum(t1, axis=-1) -
                          k + 2.0 * (jnp.sum(jnp.log(d2), axis=-1) -
                                     jnp.sum(jnp.log(d1), axis=-1)))

        return apply(impl, (self.loc, self.scale, other.loc, other.scale),
                     name="mvn_diag_kl")
