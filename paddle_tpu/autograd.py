"""paddle_tpu.autograd — tape-based reverse-mode autograd for dygraph.

TPU-native rebuild of the reference's imperative autograd engine
(reference: paddle/fluid/imperative/tracer.cc + engine.cc, and
python/paddle/fluid/dygraph/base.py for no_grad/guard semantics).

Design: instead of recording grad *ops* into a graph and replaying them on a
C++ engine, each forward op records a `jax.vjp` closure (a TapeNode). At
``loss.backward()`` we walk the recorded nodes in reverse creation order and
accumulate cotangents into every reachable Tensor with
``stop_gradient=False``. All of this is jit-traceable: under
``jit.to_static`` the same tape runs on tracers and the whole
forward+backward collapses into one XLA computation.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor

float0 = jax.dtypes.float0


class TapeNode:
    """One recorded op: inputs, a vjp closure, and weak links to outputs."""
    __slots__ = ("inputs", "vjp", "outputs", "seq", "name")

    _counter = [0]

    def __init__(self, inputs, vjp, outputs, name=""):
        self.inputs = inputs          # list[Tensor]
        self.vjp = vjp                # cotangents(tuple) -> tuple of in-grads
        self.outputs = outputs        # list[Tensor] (strong refs are fine:
                                      # the graph dies with the step)
        TapeNode._counter[0] += 1
        self.seq = TapeNode._counter[0]
        self.name = name


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True


_state = _State()


def grad_enabled():
    return _state.grad_enabled


@contextlib.contextmanager
def no_grad():
    """Disable tape recording (reference: fluid.dygraph.no_grad)."""
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


def no_grad_(fn):
    """Decorator form of no_grad."""
    def wrapper(*args, **kwargs):
        with no_grad():
            return fn(*args, **kwargs)
    return wrapper


@contextlib.contextmanager
def enable_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


def _zero_cotangent(arr):
    dt = jnp.result_type(arr)
    if jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating):
        return jnp.zeros(arr.shape, dt)
    return np.zeros(arr.shape, float0)


def backward(root: Tensor, grad_tensor=None, retain_graph=False, _only=None):
    """Reverse sweep from ``root``; accumulates into ``t._grad`` for every
    reachable tensor with stop_gradient=False (reference semantics of
    VarBase.backward + gradient accumulation until clear_gradients)."""
    if root._tape_node is None:
        if root._graph_freed:
            raise RuntimeError(
                "Trying to backward through a graph that has already been "
                "freed. Pass retain_graph=True to the first backward() if "
                "you need to backward twice.")
        return
    if grad_tensor is None:
        seed = jnp.ones(root.data.shape, jnp.result_type(root.data))
    else:
        seed = grad_tensor.data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # Collect reachable nodes.
    nodes = []
    seen = set()
    stack = [root._tape_node]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes.append(node)
        for t in node.inputs:
            if t._tape_node is not None and id(t._tape_node) not in seen:
                stack.append(t._tape_node)
    nodes.sort(key=lambda n: n.seq, reverse=True)

    # Cotangent accumulator keyed by tensor identity. Reverse-topological
    # order guarantees a tensor's cotangent is complete when its producing
    # node is processed (all consumers ran first).
    cotangents = {id(root): seed}
    holders = {id(root): root}

    def _clip_err(t, ct):
        # reference ErrorClipByValue (fluid/clip.py): a per-var clip on
        # the INCOMING error signal — affects both the stored .grad and
        # everything propagated further upstream
        eclip = getattr(t, "error_clip", None)
        return ct if eclip is None else eclip(ct)

    def _accumulate_grad(t, ct):
        if t.stop_gradient or (_only is not None and id(t) not in _only):
            return
        t._grad = ct if t._grad is None else t._grad + ct

    for node in nodes:
        outs_ct = []
        any_ct = False
        for o in node.outputs:
            ct = cotangents.pop(id(o), None)
            holders.pop(id(o), None)
            if ct is None:
                ct = _zero_cotangent(o.data)
            else:
                ct = _clip_err(o, ct)
                any_ct = True
                _accumulate_grad(o, ct)
            outs_ct.append(ct)
        if not any_ct:
            continue
        if node.vjp is None:
            raise RuntimeError(
                "Trying to backward through a graph that has been freed "
                f"(op '{node.name}'). Call backward(retain_graph=True) on "
                "the first backward if you need to backward twice.")
        in_grads = node.vjp(tuple(outs_ct) if len(outs_ct) > 1 else outs_ct[0])
        for t, g in zip(node.inputs, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == float0):
                continue
            if t.stop_gradient and t._tape_node is None:
                continue  # dead end: nothing downstream wants this grad
            if t._tape_node is None and t._graph_freed:
                raise RuntimeError(
                    "Trying to backward through a sub-graph that has "
                    "already been freed (shared intermediate "
                    f"feeding op '{node.name}'). Use retain_graph=True.")
            prev = cotangents.get(id(t))
            cotangents[id(t)] = g if prev is None else prev + g
            holders[id(t)] = t

    # Whatever is left in the accumulator belongs to leaf tensors.
    for key, ct in cotangents.items():
        _accumulate_grad(holders[key], _clip_err(holders[key], ct))

    if not retain_graph:
        for node in nodes:
            node.vjp = None
        for node in nodes:
            for o in node.outputs:
                o._tape_node = None
                o._graph_freed = True


def grad(outputs, inputs, grad_outputs=None, retain_graph=False):
    """Functional gradient a la paddle.grad: returns grads of outputs wrt
    inputs without touching .grad accumulators."""
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    saved = [(t, t._grad) for t in inputs]
    for t in inputs:
        t._grad = None
    saved_flags = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    try:
        only = {id(t) for t in inputs}
        for i, out in enumerate(outputs):
            g = None if grad_outputs is None else grad_outputs[i]
            backward(out, g, retain_graph=True, _only=only)
        results = [t._grad if t._grad is not None else
                   jnp.zeros(t.data.shape, t.data.dtype) for t in inputs]
        results = [Tensor(r, stop_gradient=True) for r in results]
    finally:
        for (t, g), flag in zip(saved, saved_flags):
            t._grad = g
            t.stop_gradient = flag
        if not retain_graph:
            for out in outputs:
                clear_graph(out)
    return results if len(results) > 1 else results[0]


def clear_graph(root):
    if root._tape_node is None:
        return
    stack = [root._tape_node]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for t in node.inputs:
            if t._tape_node is not None:
                stack.append(t._tape_node)
        node.vjp = None
        for o in node.outputs:
            o._tape_node = None
            o._graph_freed = True
