"""paddle_tpu.ops.creation — tensor creation + random ops.

TPU-native rebuild of the reference's fill/creation operators
(reference: paddle/fluid/operators/{fill_constant_op, uniform_random_op,
gaussian_random_op, range_op, linspace_op, eye}.cc; python surface in
fluid/layers/tensor.py). Random ops draw subkeys from the global threaded
PRNG (paddle_tpu.random) instead of stateful curand generators.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor, as_tensor, convert_dtype, get_default_dtype
from ..dispatch import apply
from .. import random as prandom


def _dt(dtype, default=None):
    dt = convert_dtype(dtype)
    return dt if dt is not None else (default or get_default_dtype())


def zeros(shape, dtype="float32", name=None):
    return Tensor(jnp.zeros(tuple(shape), _dt(dtype)))


def ones(shape, dtype="float32", name=None):
    return Tensor(jnp.ones(tuple(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    return Tensor(jnp.full(tuple(shape), fill_value, _dt(dtype)))


fill_constant = full


def zeros_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return apply(lambda x, dt: jnp.zeros(x.shape, dt or x.dtype), (x,),
                 dict(dt=convert_dtype(dtype)), nondiff=True,
                 name="zeros_like")


def ones_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return apply(lambda x, dt: jnp.ones(x.shape, dt or x.dtype), (x,),
                 dict(dt=convert_dtype(dtype)), nondiff=True,
                 name="ones_like")


def full_like(x, fill_value, dtype=None, name=None):
    x = as_tensor(x)
    return apply(lambda x, v, dt: jnp.full(x.shape, v, dt or x.dtype), (x,),
                 dict(v=fill_value, dt=convert_dtype(dtype)), nondiff=True,
                 name="full_like")


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    dt = convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=dt))


range = arange


def linspace(start, stop, num, dtype="float32", name=None):
    return Tensor(jnp.linspace(start, stop, num, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def assign(x, output=None):
    """reference: assign_op.cc. Inside an active Switch case block the
    write is deferred and merged first-match-wins at Switch exit
    (reference: the guarded sub-block assign in control_flow.py:Switch)."""
    x = as_tensor(x)
    if output is not None:
        from .imperative_flow import Switch
        if Switch.in_case_block():
            Switch.active()._register(x, output)
            return output
    out = apply(lambda x: x + 0, (x,), name="assign")
    if output is not None:
        output.set_value(out.data)
        return output
    return out


def clone(x, name=None):
    return apply(lambda x: x + 0, (x,), name="clone")


# ---------------------------------------------------------------------------
# random creation — global threaded PRNG key, jit-friendly

def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    """reference: uniform_random_op.cc"""
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()
    return Tensor(jax.random.uniform(key, tuple(shape), _dt(dtype),
                                     minval=min, maxval=max))


uniform_random = uniform
rand = lambda shape, dtype="float32": uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype="float32", name=None):
    return Tensor(jax.random.normal(prandom.next_key(), tuple(shape),
                                    _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    """reference: gaussian_random_op.cc"""
    out = jax.random.normal(prandom.next_key(), tuple(shape), get_default_dtype())
    return Tensor(out * std + mean)


gaussian = normal


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(prandom.next_key(), tuple(shape), low,
                                     high, dtype=_dt(dtype, jnp.int64)))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(prandom.next_key(),
                                         n).astype(_dt(dtype, jnp.int64)))


def bernoulli(x, name=None):
    x = as_tensor(x)
    key = prandom.next_key()
    return apply(lambda x, key: jax.random.bernoulli(
        key, x).astype(x.dtype), (x,), dict(key=key), nondiff=True,
        name="bernoulli")


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    key = prandom.next_key()
    def impl(x, key, num_samples, replacement):
        logits = jnp.log(jnp.maximum(x, 1e-30))
        idt = convert_dtype("int64")
        if replacement:
            out = jax.random.categorical(
                key, logits, axis=-1, shape=(num_samples,) + x.shape[:-1])
            return jnp.moveaxis(out, 0, -1).astype(idt)
        # without replacement: Gumbel top-k over the category axis
        g = jax.random.gumbel(key, logits.shape, logits.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(idt)
    return apply(impl, (x,), dict(key=key, num_samples=num_samples,
                                  replacement=replacement), nondiff=True,
                 name="multinomial")
