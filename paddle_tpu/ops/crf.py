"""paddle_tpu.ops.crf — linear-chain CRF (training loss + viterbi decode).

TPU-native rebuild of the reference's CRF operators
(reference: paddle/fluid/operators/linear_chain_crf_op.cc/.h and
crf_decoding_op.h; python surface fluid/layers/nn.py:linear_chain_crf /
crf_decoding).

Parameter layout matches the reference: ``transition`` is
``[num_tags + 2, num_tags]`` — row 0 holds start weights, row 1 holds end
weights, rows 2.. hold the tag→tag transition matrix.

TPU-first redesign: the reference walks ragged LoD sequences in C++ with
per-sequence loops; here emissions are the padded ``[B, T, D]`` batch plus
``length [B]`` and both the forward algorithm (log-partition) and viterbi
run as a single ``lax.scan`` over time with masked carries — one compiled
program for the whole batch, MXU-friendly [B, D, D] broadcasts, no host
loops. Gradients come from jax autodiff of the log-partition (which IS the
CRF marginal-based gradient), replacing the hand-written backward op.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import as_tensor
from ..dispatch import apply

NEG_INF = -1e30


def _split_transition(transition):
    start = transition[0]          # [D]
    end = transition[1]            # [D]
    trans = transition[2:]         # [D, D] (from, to)
    return start, end, trans


def _crf_nll(emission, transition, label, length):
    """Negative log-likelihood per sequence: [B] (fp32)."""
    emission = emission.astype(jnp.float32)
    transition = transition.astype(jnp.float32)
    b, t, d = emission.shape
    start, end, trans = _split_transition(transition)
    label = label.astype(jnp.int32)
    ln = length.astype(jnp.int32)

    # ---- log partition via forward algorithm --------------------------
    alpha0 = start[None, :] + emission[:, 0]           # [B, D]

    def fwd(alpha, inp):
        emit_t, step = inp                             # [B, D], scalar
        # logsumexp over previous tag: [B, D_prev, 1] + [D_prev, D_to]
        scores = alpha[:, :, None] + trans[None]
        new = jax.scipy.special.logsumexp(scores, axis=1) + emit_t
        keep = (step < ln)[:, None]                    # step beyond len?
        alpha = jnp.where(keep, new, alpha)
        return alpha, None

    steps = jnp.arange(1, t)
    alpha, _ = jax.lax.scan(fwd, alpha0,
                            (jnp.moveaxis(emission[:, 1:], 1, 0), steps))
    log_z = jax.scipy.special.logsumexp(alpha + end[None, :], axis=1)

    # ---- gold path score ---------------------------------------------
    first_tag = label[:, 0]
    score = start[first_tag] + emission[jnp.arange(b), 0, first_tag]

    def path(score, inp):
        prev_y, y, emit_t, step = inp
        add = trans[prev_y, y] + emit_t[jnp.arange(b), y]
        return jnp.where(step < ln, score + add, score), None

    score, _ = jax.lax.scan(
        path, score,
        (jnp.moveaxis(label[:, :-1], 1, 0), jnp.moveaxis(label[:, 1:], 1, 0),
         jnp.moveaxis(emission[:, 1:], 1, 0), steps))
    last_tag = jnp.take_along_axis(label, jnp.maximum(ln - 1, 0)[:, None],
                                   axis=1)[:, 0]
    score = score + end[last_tag]

    return log_z - score


def linear_chain_crf(input, label, transition, length=None, name=None):
    """reference: fluid/layers/nn.py:linear_chain_crf (op
    linear_chain_crf_op.cc). Returns the per-sequence negative
    log-likelihood ``[B, 1]`` (the value the reference calls
    ``log_likelihood`` and feeds straight to ``mean`` as a cost).

    input: emissions [B, T, D]; label: [B, T] int; transition:
    [D+2, D] Parameter; length: [B] (None = full width)."""
    input = as_tensor(input)

    def impl(emission, transition, label, *maybe_len):
        b, t, d = emission.shape
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        return _crf_nll(emission, transition, label, ln)[:, None]

    args = [input, transition, as_tensor(label)]
    if length is not None:
        args.append(as_tensor(length))
    return apply(impl, tuple(args), name="linear_chain_crf")


def crf_decoding(input, transition, label=None, length=None, name=None):
    """reference: fluid/layers/nn.py:crf_decoding (crf_decoding_op.h) —
    viterbi decode. Returns [B, T] best tag path (zeros past `length`).
    When `label` is given, returns [B, T] 0/1 correctness mask like the
    reference (1 where decoded == label, within the valid prefix)."""
    input = as_tensor(input)
    has_label = label is not None
    has_len = length is not None

    def impl(emission, transition, *rest, has_label, has_len):
        emission = emission.astype(jnp.float32)
        transition = transition.astype(jnp.float32)
        lab = rest[0] if has_label else None
        ln = rest[1 if has_label else 0] if has_len else None
        b, t, d = emission.shape
        if ln is None:
            ln = jnp.full((b,), t, jnp.int32)
        ln = ln.astype(jnp.int32)
        start, end, trans = _split_transition(transition)

        alpha0 = start[None, :] + emission[:, 0]

        def fwd(alpha, inp):
            emit_t, step = inp
            scores = alpha[:, :, None] + trans[None]      # [B, from, to]
            best_prev = jnp.argmax(scores, axis=1)        # [B, to]
            new = jnp.max(scores, axis=1) + emit_t
            keep = (step < ln)[:, None]
            alpha = jnp.where(keep, new, alpha)
            # backpointer for padded steps: identity (keeps tag)
            bp = jnp.where(keep, best_prev,
                           jnp.arange(d)[None, :].repeat(b, 0))
            return alpha, bp

        steps = jnp.arange(1, t)
        alpha, bps = jax.lax.scan(
            fwd, alpha0, (jnp.moveaxis(emission[:, 1:], 1, 0), steps))
        # bps: [T-1, B, D]
        last = jnp.argmax(alpha + end[None, :], axis=1)   # [B]

        def back(tag, bp):
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        first, tags_rev = jax.lax.scan(back, last, bps, reverse=True)
        path = jnp.concatenate([first[None], tags_rev], axis=0)  # [T, B]
        path = jnp.moveaxis(path, 0, 1)                    # [B, T]
        valid = jnp.arange(t)[None, :] < ln[:, None]
        path = jnp.where(valid, path, 0)
        if lab is not None:
            ok = (path == lab.astype(jnp.int32)).astype(jnp.int32)
            return jnp.where(valid, ok, 0)
        return path

    args = [input, transition]
    if has_label:
        args.append(as_tensor(label))
    if has_len:
        args.append(as_tensor(length))
    return apply(impl, tuple(args), dict(has_label=has_label,
                                         has_len=has_len),
                 nondiff=True, name="crf_decoding")
