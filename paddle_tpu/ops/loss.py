"""paddle_tpu.ops.loss — loss functional ops.

TPU-native rebuild of the reference's loss operators
(reference: paddle/fluid/operators/{cross_entropy_op,
softmax_with_cross_entropy_op, sigmoid_cross_entropy_with_logits_op,
squared_l2_op, huber_loss_op, kldiv_loss_op, smooth_l1_loss_op,
margin_rank_loss_op, rank_loss_op, hinge_loss_op, bpr_loss_op,
log_loss_op}.cc; python surface in fluid/layers/loss.py).

softmax_with_cross_entropy is the fused hot path (the reference has a
dedicated CUDA kernel); here the XLA logsumexp formulation fuses it, and a
Pallas kernel (ops/pallas/softmax_xent.py) covers the flagship path.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from contextlib import nullcontext

from ..tensor import as_tensor
from ..dispatch import apply
from ..monitor import profile as _profile
from . import math as _math
from . import nn_ops as _nn


def _pscope(name):
    """named_scope(F.<name>) when profiling is armed, else a no-op —
    one flag check, so the disabled path stays free."""
    if _profile.scopes_on:
        return jax.named_scope(_profile.fscope(name))
    return nullcontext()


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def _picked_logp(logp, label, axis, ignore_index):
    """Gather log-probs at hard labels, masking label==ignore_index (any
    value, incl. negatives — indices are clamped before the gather so OOB
    labels can't alias a real class). Returns (loss, valid_mask)."""
    lbl = label
    ax = axis % logp.ndim
    if lbl.ndim == logp.ndim and lbl.shape[ax] == 1:
        lbl = jnp.squeeze(lbl, ax)
    valid = lbl != ignore_index
    nclass = logp.shape[ax]
    safe = jnp.clip(lbl, 0, nclass - 1).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, ax), axis=ax)
    loss = jnp.where(jnp.expand_dims(valid, ax), -picked, 0.0)
    return loss, valid


def _fused_softmax_xent(x, label, ignore_index):
    """Per-position loss via the Pallas fused kernel when enabled, else
    None. The kernel scores every row ([N,V] softmax never hits HBM; an
    ignored/OOB label matches no column → loss=lse there); masking after
    also zeroes the cotangent into the kernel's backward at those rows.
    Returns (loss[lead+(1,)] in x.dtype, valid[lead])."""
    from .pallas import enabled
    if not enabled("softmax_xent"):
        return None
    from .pallas.softmax_xent import _softmax_xent2
    v = x.shape[-1]
    lbl = label
    if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
        lbl = jnp.squeeze(lbl, -1)
    valid = lbl != ignore_index
    loss = _softmax_xent2(
        x.reshape(-1, v), lbl.reshape(-1, 1).astype(jnp.int32)
    ).reshape(lbl.shape + (1,)).astype(x.dtype)
    return jnp.where(valid[..., None], loss, jnp.zeros((), x.dtype)), valid


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False, name=None):
    """Fused, numerically stable (reference: the fused CUDA kernel in
    softmax_with_cross_entropy_op.cu)."""
    def impl(logits, label, soft_label, ignore_index, axis, return_softmax):
        ax = axis % logits.ndim
        if not soft_label and not return_softmax and ax == logits.ndim - 1:
            fused = _fused_softmax_xent(logits, label, ignore_index)
            if fused is not None:
                return fused[0]
        lse = jax.scipy.special.logsumexp(logits, axis=axis, keepdims=True)
        logp = logits - lse
        if soft_label:
            loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
        else:
            loss, _ = _picked_logp(logp, label, axis, ignore_index)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss
    with _pscope("F.softmax_with_cross_entropy"):
        out = apply(impl, (logits, label),
                    dict(soft_label=soft_label, ignore_index=ignore_index,
                         axis=axis, return_softmax=return_softmax),
                    n_out=2 if return_softmax else 1,
                    name="softmax_with_cross_entropy")
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  reduction="mean", axis=-1, use_softmax=True,
                  weight=None, name=None):
    """paddle.nn.functional.cross_entropy parity: input is logits when
    use_softmax (default), else probabilities (reference cross_entropy_op).
    `weight` is a per-class weight vector; mean reduction normalizes by the
    summed weights of non-ignored positions (paddle semantics)."""
    def impl(x, label, *maybe_w, soft_label, ignore_index, axis, use_softmax,
             reduction):
        ax = axis % x.ndim
        if soft_label or not (use_softmax and ax == x.ndim - 1):
            fused = None
        else:
            fused = _fused_softmax_xent(x, label, ignore_index)
        if fused is None:
            if use_softmax:
                logp = x - jax.scipy.special.logsumexp(x, axis=axis,
                                                       keepdims=True)
            else:
                logp = jnp.log(jnp.clip(x, 1e-10, 1.0))
        if soft_label:
            loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
            denom_w = jnp.ones_like(loss)
        else:
            if fused is not None:
                loss, valid = fused
            else:
                loss, valid = _picked_logp(logp, label, axis, ignore_index)
            lbl = label
            if lbl.ndim == x.ndim and lbl.shape[ax] == 1:
                lbl = jnp.squeeze(lbl, ax)
            safe = jnp.clip(lbl, 0, x.shape[ax] - 1).astype(jnp.int32)
            if maybe_w:
                w = jnp.expand_dims(maybe_w[0][safe], ax)
                loss = loss * w
                denom_w = jnp.where(jnp.expand_dims(valid, ax), w, 0.0)
            else:
                denom_w = jnp.expand_dims(valid, ax).astype(loss.dtype)
        if reduction == "none":
            return loss
        if reduction == "sum":
            return jnp.sum(loss)
        return jnp.sum(loss) / jnp.maximum(jnp.sum(denom_w), 1e-12)

    args = (input, label) if weight is None else (input, label, weight)
    with _pscope("F.cross_entropy"):
        return apply(impl, args,
                     dict(soft_label=soft_label, ignore_index=ignore_index,
                          axis=axis, use_softmax=use_softmax,
                          reduction=reduction), name="cross_entropy")


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    """reference: sigmoid_cross_entropy_with_logits_op.cc"""
    def impl(x, label, ignore_index, normalize):
        loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
        mask = label != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if normalize:
            loss = loss / jnp.maximum(jnp.sum(mask), 1)
        return loss
    return apply(impl, (x, label), dict(ignore_index=ignore_index,
                                        normalize=normalize),
                 name="sigmoid_cross_entropy_with_logits")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def impl(p, label, *maybe_w, reduction):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(label * jnp.log(p) + (1 - label) * jnp.log1p(-p))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply(impl, args, dict(reduction=reduction), name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    # log-sigmoid formulation: loss = -pos_weight*y*log(sigmoid(x))
    #                                 - (1-y)*log(1-sigmoid(x)),  then *weight
    has_w = weight is not None
    has_pw = pos_weight is not None

    def impl(x, label, *extra, reduction, has_w, has_pw):
        log_sig = -jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.minimum(x, 0)
        log_one_minus = log_sig - x  # log(1 - sigmoid(x)) = log_sigmoid(-x)
        idx = 0
        pw = 1.0
        if has_pw:
            pw = extra[idx + (1 if has_w else 0)]
        loss = -(pw * label * log_sig + (1 - label) * log_one_minus)
        if has_w:
            loss = loss * extra[0]
        return _reduce(loss, reduction)

    args = [logit, label]
    if has_w:
        args.append(weight)
    if has_pw:
        args.append(pos_weight)
    return apply(impl, tuple(args),
                 dict(reduction=reduction, has_w=has_w, has_pw=has_pw),
                 name="bce_with_logits")


def square_error_cost(input, label, name=None):
    """reference: squared_l2_distance / square_error_cost"""
    return apply(lambda x, y: jnp.square(x - y), (input, label),
                 name="square_error_cost")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y, reduction: _reduce(jnp.square(x - y), reduction),
                 (input, label), dict(reduction=reduction), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y, reduction: _reduce(jnp.abs(x - y), reduction),
                 (input, label), dict(reduction=reduction), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    """reference: smooth_l1_loss_op.cc (huber form)."""
    def impl(x, y, reduction, delta):
        d = x - y
        a = jnp.abs(d)
        loss = jnp.where(a < delta, 0.5 * d * d / delta, a - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply(impl, (input, label), dict(reduction=reduction, delta=delta),
                 name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, name=None):
    def impl(x, y, delta):
        d = x - y
        a = jnp.abs(d)
        return jnp.where(a <= delta, 0.5 * d * d, delta * (a - 0.5 * delta))
    return apply(impl, (input, label), dict(delta=delta), name="huber_loss")


def kl_div(input, label, reduction="mean", name=None):
    """reference: kldiv_loss_op.cc — input is log-probabilities."""
    def impl(logp, y, reduction):
        loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-30)) - logp),
                         0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(impl, (input, label), dict(reduction=reduction),
                 name="kl_div")


def log_loss(input, label, epsilon=1e-4, name=None):
    """reference: log_loss_op.cc"""
    def impl(p, y, epsilon):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply(impl, (input, label), dict(epsilon=epsilon), name="log_loss")


def hinge_loss(input, label, name=None):
    """reference: hinge_loss_op.cc (labels in {0,1})."""
    def impl(x, y):
        return jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * x)
    return apply(impl, (input, label), name="hinge_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    """reference: margin_rank_loss_op.cc"""
    def impl(x1, x2, y, margin, reduction):
        return _reduce(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction)
    return apply(impl, (input, other, label),
                 dict(margin=margin, reduction=reduction),
                 name="margin_ranking_loss")


def rank_loss(label, left, right, name=None):
    """reference: rank_loss_op.cc (RankNet pairwise loss)."""
    def impl(label, left, right):
        d = left - right
        return jnp.log1p(jnp.exp(d)) - label * d
    return apply(impl, (label, left, right), name="rank_loss")


def bpr_loss(input, label, name=None):
    """reference: bpr_loss_op.cc (Bayesian Personalized Ranking)."""
    def impl(x, label):
        pos = jnp.take_along_axis(x, label.reshape(-1, 1).astype(jnp.int32),
                                  axis=1)
        diff = x - pos
        n = x.shape[1]
        loss = jnp.sum(jnp.log1p(jnp.exp(diff)), axis=1, keepdims=True) / (n - 1)
        return loss
    return apply(impl, (input, label), name="bpr_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def impl(logp, label, *maybe_w, ignore_index, reduction):
        valid = label != ignore_index
        safe = jnp.clip(label, 0, logp.shape[-1] - 1).astype(jnp.int32)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss = jnp.where(valid, -picked, 0.0)
        if maybe_w:
            w = maybe_w[0][safe]
            loss = loss * w
            denom = jnp.sum(jnp.where(valid, w, 0.0))
        else:
            denom = jnp.sum(valid)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return apply(impl, args, dict(ignore_index=ignore_index,
                                  reduction=reduction), name="nll_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def impl(x1, x2, axis, eps):
        n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
        n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
        return jnp.sum(x1 * x2, axis=axis) / jnp.maximum(n1 * n2, eps)
    return apply(impl, (x1, x2), dict(axis=axis, eps=eps),
                 name="cosine_similarity")
