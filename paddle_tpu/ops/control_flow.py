"""paddle_tpu.ops.control_flow — cond / while_loop / case / switch_case.

TPU-native rebuild of reference python/paddle/fluid/layers/control_flow.py
(cond, While/while_loop, case, switch_case + the C++ conditional_block and
while ops). The reference builds sub-block programs; on XLA the natural
form is `lax.cond` / `lax.while_loop` / `lax.switch` — compiled control
flow with both branches staged, no sub-block machinery.

Semantics:
* eager with a CONCRETE predicate → plain Python branching (reference
  dygraph behavior), fully differentiable through the tape;
* traced predicate (inside to_static / static Program) → lax primitive.
  cond/switch stay differentiable (jax transposes them); while_loop is
  forward-only (same restriction the reference documents for grads through
  dynamic loops — use `lax.scan`-style fixed-trip loops for training).

Values captured by branch closures are baked as constants; pass loop-
carried / branch inputs explicitly through `operands` for gradients.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor
from ..dispatch import apply
from .. import autograd as _ag


def _is_concrete(x):
    data = x.data if isinstance(x, Tensor) else x
    return not isinstance(data, jax.core.Tracer)


def _pure(fn):
    """Run a framework-ops closure as a pure array function."""
    def wrapper(args):
        with _ag.no_grad():
            out = fn(*[Tensor(a) for a in args]) if args else fn()
        flat, tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        return tuple(t.data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in flat), tree
    return wrapper


def cond(pred, true_fn, false_fn, operands=(), name=None):
    """reference: layers/control_flow.py:cond."""
    pred_t = as_tensor(pred)
    if _is_concrete(pred_t):
        taken = true_fn if bool(np.asarray(
                jax.device_get(pred_t.data)).item()) else false_fn
        return taken(*operands)

    ops_t = tuple(as_tensor(o) for o in operands)
    tree_box = {}

    def impl(pred, *arrays):
        tf = _pure(true_fn)
        ff = _pure(false_fn)

        def t_branch(args):
            out, tree = tf(args)
            tree_box["tree"] = tree
            return out

        def f_branch(args):
            out, _ = ff(args)
            return out

        return lax.cond(pred, t_branch, f_branch, arrays)

    out = apply(impl, (pred_t,) + ops_t,
                n_out=_probe_n_out(true_fn, ops_t), name="cond")
    outs = out if isinstance(out, tuple) else (out,)
    return jax.tree_util.tree_unflatten(tree_box["tree"], list(outs)) \
        if "tree" in tree_box else out


def _probe_n_out(fn, ops_t):
    """Count outputs via eval_shape on the branch (cheap, no FLOPs)."""
    def probe(*arrays):
        with _ag.no_grad():
            out = fn(*[Tensor(a) for a in arrays]) if arrays else fn()
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        return tuple(t.data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in flat)
    shapes = jax.eval_shape(probe, *[jax.ShapeDtypeStruct(
        tuple(o.shape), o.dtype) for o in ops_t])
    return len(shapes)


def while_loop(cond_fn, body_fn, loop_vars, name=None):
    """reference: layers/control_flow.py:while_loop. Forward-only under
    trace (lax.while_loop has no transpose); eager loops run in Python and
    remain differentiable."""
    vars_t = [as_tensor(v) for v in loop_vars]

    probe = cond_fn(*vars_t)
    if _is_concrete(probe):
        # eager: honest python loop through the tape
        while bool(np.asarray(jax.device_get(as_tensor(
                cond_fn(*vars_t)).data)).item()):
            out = body_fn(*vars_t)
            vars_t = [as_tensor(v) for v in (
                out if isinstance(out, (tuple, list)) else (out,))]
        return vars_t if len(vars_t) > 1 else vars_t[0]

    def impl(*arrays):
        def c(args):
            with _ag.no_grad():
                return as_tensor(cond_fn(*[Tensor(a) for a in args])).data
        def b(args):
            with _ag.no_grad():
                out = body_fn(*[Tensor(a) for a in args])
            out = out if isinstance(out, (tuple, list)) else (out,)
            return tuple(as_tensor(o).data for o in out)
        return lax.while_loop(c, b, arrays)

    out = apply(impl, tuple(vars_t), n_out=len(vars_t), nondiff=True,
                name="while_loop")
    return out if len(vars_t) > 1 else out[0]


def switch_case(branch_index, branch_fns, default=None, operands=(),
                name=None):
    """reference: layers/control_flow.py:switch_case."""
    idx_t = as_tensor(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map branch index -> dense position
        mapping = {k: i for i, k in enumerate(keys)}
    else:
        fns = list(branch_fns)
        mapping = None
    if default is not None:
        fns = fns + [default]
    ndefault = len(fns) - 1

    if _is_concrete(idx_t):
        i = int(np.asarray(jax.device_get(idx_t.data)).item())
        if mapping is not None:
            i = mapping.get(i, ndefault)
        i = min(max(i, 0), len(fns) - 1)
        return fns[i](*operands)

    ops_t = tuple(as_tensor(o) for o in operands)

    def impl(idx, *arrays):
        if mapping is not None:
            dense = jnp.full((), ndefault, jnp.int32)
            for k, i in mapping.items():
                dense = jnp.where(idx == k, i, dense)
            idx = dense
        idx = jnp.clip(idx, 0, len(fns) - 1).astype(jnp.int32)
        branches = [(lambda f: lambda args: _pure(f)(args)[0])(f)
                    for f in fns]
        return lax.switch(idx, branches, arrays)

    out = apply(impl, (idx_t,) + ops_t, n_out=_probe_n_out(fns[0], ops_t),
                name="switch_case")
    return out


def case(pred_fn_pairs, default=None, name=None):
    """reference: layers/control_flow.py:case — first true predicate wins."""
    for pred, fn in pred_fn_pairs:
        pred_t = as_tensor(pred)
        if _is_concrete(pred_t):
            if bool(np.asarray(jax.device_get(pred_t.data)).item()):
                return fn()
        else:
            rest = [(p, f) for p, f in pred_fn_pairs
                    if (p is not pred or f is not fn)]
            if rest:
                tail = lambda: case(rest, default)  # noqa: E731
            elif default is not None:
                tail = default
            else:
                raise ValueError(
                    "case() with a traced predicate needs a `default` "
                    "branch: whether any predicate matches is unknown at "
                    "trace time (reference raises at runtime instead)")
            return cond(pred_t, fn, tail)
    if default is not None:
        return default()
    raise ValueError("no predicate matched and no default given")
