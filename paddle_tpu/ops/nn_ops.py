"""paddle_tpu.ops.nn_ops — neural-net functional ops.

TPU-native rebuild of the reference's NN operators
(reference: paddle/fluid/operators/{conv_op, pool_op, batch_norm_op,
layer_norm_op, group_norm_op, instance_norm_op, softmax_op, dropout_op,
lookup_table_op, interpolate_op, prelu_op}.cc/.cu; python surface in
python/paddle/fluid/layers/nn.py).

TPU-first choices:
* convs lower to one `lax.conv_general_dilated` (MXU); NCHW accepted for
  API parity but internally dims are passed via dimension_numbers so XLA
  picks the TPU-friendly layout — no manual im2col as in the CUDA kernels.
* normalizations are fused arithmetic XLA folds into neighbouring matmuls;
  a Pallas fused layer_norm lives in paddle_tpu/ops/pallas for the hot path.
* dropout threads the global PRNG key (see paddle_tpu.random) — no curand.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from contextlib import nullcontext

from ..tensor import Tensor, as_tensor, convert_dtype
from ..dispatch import apply
from ..monitor import profile as _profile
from .. import random as prandom


def _pscope(name):
    """named_scope(F.<name>) when profiling is armed, else a no-op —
    one flag check, so the disabled path stays free."""
    if _profile.scopes_on:
        return jax.named_scope(_profile.fscope(name))
    return nullcontext()


# ---------------------------------------------------------------------------
# activations (reference: activation_op.cc, gelu_op, prelu_op)

def relu(x, name=None):
    return apply(lambda x: jnp.maximum(x, 0), (x,), name="relu")


def relu6(x, name=None):
    return apply(lambda x: jnp.clip(x, 0, 6), (x,), name="relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda x, a: jnp.where(x >= 0, x, a * x), (x,),
                 dict(a=negative_slope), name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(x, w):
        if w.size == 1:
            wb = w.reshape(())
        elif data_format == "NCHW" and x.ndim > 2:
            wb = w.reshape((1, -1) + (1,) * (x.ndim - 2))
        else:
            wb = w
        return jnp.where(x >= 0, x, wb * x)
    return apply(impl, (x, weight), name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda x, a: jnp.where(x > 0, x, a * jnp.expm1(x)), (x,),
                 dict(a=alpha), name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda x, s, a: s * jnp.where(x > 0, x, a * jnp.expm1(x)),
                 (x,), dict(s=scale, a=alpha), name="selu")


def gelu(x, approximate=False, name=None):
    return apply(lambda x, approximate: jax.nn.gelu(x, approximate=approximate),
                 (x,), dict(approximate=approximate), name="gelu")


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, (x,), name="sigmoid")


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, (x,), name="log_sigmoid")


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return apply(lambda x, s, o: jnp.clip(s * x + o, 0.0, 1.0), (x,),
                 dict(s=slope, o=offset), name="hard_sigmoid")


def hard_swish(x, name=None):
    return apply(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0, (x,),
                 name="hard_swish")


def swish(x, name=None):
    return apply(lambda x: x * jax.nn.sigmoid(x), (x,), name="swish")


silu = swish


def mish(x, name=None):
    return apply(lambda x: x * jnp.tanh(jax.nn.softplus(x)), (x,),
                 name="mish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda x, b, t: jnp.where(
        b * x > t, x, jax.nn.softplus(b * x) / b), (x,),
        dict(b=beta, t=threshold), name="softplus")


def softsign(x, name=None):
    return apply(lambda x: x / (1 + jnp.abs(x)), (x,), name="softsign")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda x, t: jnp.where(x > t, x - t,
                                        jnp.where(x < -t, x + t, 0.0)),
                 (x,), dict(t=threshold), name="softshrink")


def hard_shrink(x, threshold=0.5, name=None):
    """reference: layers/ops.py:113 hard_shrink."""
    t = 0.5 if threshold is None else threshold
    return apply(lambda x, t: jnp.where(jnp.abs(x) > t, x, 0.0), (x,),
                 dict(t=t), name="hard_shrink")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda x, lo, hi: jnp.clip(x, lo, hi), (x,),
                 dict(lo=min, hi=max), name="hardtanh")


def tanhshrink(x, name=None):
    return apply(lambda x: x - jnp.tanh(x), (x,), name="tanhshrink")


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda x, t: jnp.where(x > t, x, 0.0), (x,),
                 dict(t=threshold), name="thresholded_relu")


def maxout(x, groups, axis=1, name=None):
    def impl(x, groups, axis):
        c = x.shape[axis]
        new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
        return jnp.max(x.reshape(new_shape), axis=axis + 1)
    return apply(impl, (x,), dict(groups=groups, axis=axis), name="maxout")


def softmax(x, axis=-1, name=None):
    """reference: softmax_op.cc — one fused XLA softmax."""
    with _pscope("F.softmax"):
        return apply(lambda x, axis: jax.nn.softmax(x, axis=axis), (x,),
                     dict(axis=axis), name="softmax")


def log_softmax(x, axis=-1, name=None):
    with _pscope("F.log_softmax"):
        return apply(lambda x, axis: jax.nn.log_softmax(x, axis=axis), (x,),
                     dict(axis=axis), name="log_softmax")


# ---------------------------------------------------------------------------
# linear / embedding

def linear(x, weight, bias=None, name=None):
    """fc core (reference: mul_op + elementwise_add bias in fc layer):
    x @ W + b in one dot for the MXU. AMP white-listed."""
    from .. import amp
    from .math import cast as _cast
    if amp.is_enabled():
        dt = amp.compute_dtype()
        x, weight = _cast(x, dt), _cast(weight, dt)
        bias = None if bias is None else _cast(bias, dt)
    if bias is None:
        return apply(lambda x, w: jnp.matmul(x, w), (x, weight),
                     name="linear")
    return apply(lambda x, w, b: jnp.matmul(x, w) + b, (x, weight, bias),
                 name="linear")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference: lookup_table_op.cc. TPU: a gather; rows at padding_idx
    produce zeros and receive no gradient (mask trick keeps it one fused
    gather + where instead of the CUDA scatter-special-case)."""
    def impl(ids, w, padding_idx):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(impl, (x, weight), dict(padding_idx=padding_idx),
                 name="embedding")


# ---------------------------------------------------------------------------
# convolution (reference: conv_op.cc/conv_cudnn_op.cu)

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_dimension_numbers(ndim, data_format):
    # weights are ALWAYS OIHW/OIDHW (reference parity — state dicts stay
    # layout-independent); only the activation layout varies, which lax
    # supports via mixed dimension numbers
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else (
            "NHWC", "OIHW", "NHWC")
    return ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else (
        "NDHWC", "OIDHW", "NDHWC")


def _norm_padding(padding, nsp):
    """paddle padding: int, pair list, 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nsp
    padding = list(padding)
    if len(padding) == nsp and not isinstance(padding[0], (list, tuple)):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    return [tuple(p) for p in padding]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """One lax.conv_general_dilated → single MXU conv (no im2col).
    AMP white-listed."""
    from .. import amp
    from .math import cast as _cast
    if amp.is_enabled():
        dt = amp.compute_dtype()
        x, weight = _cast(x, dt), _cast(weight, dt)
        bias = None if bias is None else _cast(bias, dt)
    nsp = 2
    dn = _conv_dimension_numbers(4, data_format)
    attrs = dict(stride=_pair(stride, nsp), padding=_norm_padding(padding, nsp),
                 dilation=_pair(dilation, nsp), groups=groups, dn=dn)

    def impl(x, w, *maybe_bias, stride, padding, dilation, groups, dn):
        out = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=padding,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=dn)
        if maybe_bias:
            b = maybe_bias[0]
            if dn[2] == "NCHW":
                out = out + b.reshape(1, -1, 1, 1)
            else:
                out = out + b.reshape(1, 1, 1, -1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(impl, args, attrs, name="conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    nsp = 3
    dn = _conv_dimension_numbers(5, data_format)
    attrs = dict(stride=_pair(stride, nsp), padding=_norm_padding(padding, nsp),
                 dilation=_pair(dilation, nsp), groups=groups, dn=dn)

    def impl(x, w, *maybe_bias, stride, padding, dilation, groups, dn):
        out = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=padding,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=dn)
        if maybe_bias:
            b = maybe_bias[0]
            shape = ((1, -1) + (1,) * 3) if dn[2] == "NCDHW" else (
                (1,) * 4 + (-1,))
            out = out + b.reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(impl, args, attrs, name="conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", name=None):
    """reference: conv_transpose_op.cc. Expressed as an lhs-dilated conv of
    the gradient — XLA lowers this straight onto the MXU.

    The weight is ALWAYS the reference's IOHW layout
    (in, out/groups, kh, kw), regardless of data_format (which only
    describes the activations)."""
    nsp = 2
    lhs_spec = data_format  # "NCHW" or "NHWC"
    dn = (lhs_spec, "OIHW", lhs_spec)
    stride_t = _pair(stride, nsp)
    pad = _norm_padding(padding, nsp)
    dil = _pair(dilation, nsp)
    outpad = _pair(output_padding, nsp)

    def impl(x, w, *maybe_bias):
        kdims = w.shape[2:]
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # transpose padding math: effective pad = d*(k-1) - p
            padding_cfg = [
                (dil[i] * (kdims[i] - 1) - pad[i][0],
                 dil[i] * (kdims[i] - 1) - pad[i][1] + outpad[i])
                for i in range(nsp)]
        if groups > 1:
            # per-group: (in/g, out/g, kh, kw) -> (out/g, in/g, kh, kw)
            ci = w.shape[0]
            w_g = w.reshape(groups, ci // groups, *w.shape[1:])
            w_t = jnp.concatenate(
                [jnp.flip(w_g[g], axis=(2, 3)).swapaxes(0, 1)
                 for g in range(groups)], axis=0)
        else:
            # (in, out, kh, kw) -> flip spatial, swap io -> (out, in, kh, kw)
            w_t = jnp.flip(w, axis=(2, 3)).swapaxes(0, 1)
        out = lax.conv_general_dilated(
            x, w_t, window_strides=(1, 1), padding=padding_cfg,
            lhs_dilation=stride_t, rhs_dilation=dil,
            feature_group_count=groups, dimension_numbers=dn)
        if maybe_bias:
            b = maybe_bias[0]
            if data_format == "NCHW":
                out = out + b.reshape(1, -1, 1, 1)
            else:
                out = out + b.reshape(1, 1, 1, -1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(impl, args, name="conv2d_transpose")


# ---------------------------------------------------------------------------
# pooling (reference: pool_op.cc) — lax.reduce_window

def _pool(x, kind, kernel, stride, padding, data_format, ceil_mode=False,
          exclusive=True, global_pool=False):
    def impl(x, kernel, stride, padding, data_format, global_pool):
        nd = x.ndim
        nsp = nd - 2
        if global_pool:
            kernel = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
            stride = kernel
            padding = [(0, 0)] * nsp
        kernel = _pair(kernel, nsp)
        stride = _pair(stride if stride is not None else kernel, nsp)
        pad = _norm_padding(padding, nsp)
        if data_format in ("NCHW", "NCDHW"):
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = ([(0, 0), (0, 0)] + pad) if not isinstance(pad, str) else pad
        else:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = ([(0, 0)] + pad + [(0, 0)]) if not isinstance(pad, str) else pad
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else (
                jnp.iinfo(x.dtype).min)
            return lax.reduce_window(x, init, lax.max, window, strides, pads)
        # avg
        ones = jnp.ones_like(x)
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        if exclusive and not isinstance(pads, str):
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            return s / cnt
        return s / float(np.prod(kernel))

    return apply(impl, (x,), dict(kernel=kernel, stride=stride,
                                  padding=padding, data_format=data_format,
                                  global_pool=global_pool),
                 name=f"{kind}_pool")


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW", name=None):
    return _pool(x, "max", kernel_size, stride, padding, data_format,
                 ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               data_format="NCHW", name=None):
    return _pool(x, "avg", kernel_size, stride, padding, data_format,
                 exclusive=exclusive)


def _adaptive_pool2d(x, output_size, data_format, reduce_name):
    """Adaptive pooling with paddle's start/end-index formula — handles
    non-divisible spatial sizes (the divisible case stays a single reshape)."""
    def impl(x, output_size, data_format):
        os_ = _pair(output_size, 2)
        chan_last = data_format == "NHWC"
        if chan_last:
            x = jnp.moveaxis(x, -1, 1)
        n, c, h, w = x.shape
        red = jnp.mean if reduce_name == "avg" else jnp.max
        if h % os_[0] == 0 and w % os_[1] == 0:
            x6 = x.reshape(n, c, os_[0], h // os_[0], os_[1], w // os_[1])
            out = red(x6, axis=(3, 5))
        else:
            rows = []
            for i in range(os_[0]):
                h0, h1 = (i * h) // os_[0], -(-((i + 1) * h) // os_[0])
                cols = []
                for j in range(os_[1]):
                    w0, w1 = (j * w) // os_[1], -(-((j + 1) * w) // os_[1])
                    cols.append(red(x[:, :, h0:h1, w0:w1], axis=(2, 3)))
                rows.append(jnp.stack(cols, axis=-1))
            out = jnp.stack(rows, axis=-2)
        if chan_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(impl, (x,), dict(output_size=output_size,
                                  data_format=data_format),
                 name=f"adaptive_{reduce_name}_pool2d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool2d(x, output_size, data_format, "avg")


def adaptive_max_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool2d(x, output_size, data_format, "max")


def pool2d(x, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, data_format="NCHW", name=None):
    """fluid.layers.pool2d parity wrapper."""
    return _pool(x, "max" if pool_type == "max" else "avg", pool_size,
                 pool_stride, pool_padding, data_format,
                 global_pool=global_pooling)


# ---------------------------------------------------------------------------
# normalization (reference: batch_norm_op.cc, layer_norm_op.cu fused kernel,
# group_norm_op, instance_norm_op)

def _one_pass_moments(x, axes, keepdims=False):
    """(mean, var) over `axes` reading x ONCE: sum and sum-of-squares
    land in the same XLA multi-output fusion, vs jnp.mean + jnp.var's
    two sequential passes (the HBM-bound cost that dominates norm-heavy
    conv nets). Accumulates in f32, shifted by a stop_gradient sample
    (variance is shift-invariant) so large-mean inputs don't cancel."""
    xf = x.astype(jnp.float32)
    n = np.prod([x.shape[a] for a in axes])
    c = lax.stop_gradient(xf[tuple(
        slice(0, 1) if a in axes else slice(None)
        for a in range(x.ndim))])
    xs = xf - c
    m_s = jnp.sum(xs, axis=axes, keepdims=keepdims) / n
    mean = m_s + (c if keepdims else jnp.squeeze(c, axis=axes))
    var = jnp.maximum(
        jnp.sum(jnp.square(xs), axis=axes, keepdims=keepdims) / n -
        jnp.square(m_s), 0.0)
    return mean, var


def _fold_scale_shift(x, mean, var, w, b, epsilon, shape):
    """Fold (mean, var, w, b) into ONE per-channel scale+shift applied
    in x's compute dtype: under amp the whole elementwise chain (and
    the residual adds downstream) stays bf16 instead of promoting to
    f32, halving HBM traffic on the BN→relu→add path. w/b may be None
    (no-affine). Shared by batch_norm and SyncBatchNorm so the
    amp-sensitive folding can't drift between the SPMD and local
    paths."""
    inv = lax.rsqrt(var.astype(jnp.float32) + epsilon)
    scale, shift = inv, -mean.astype(jnp.float32) * inv
    if w is not None:
        scale = inv * w.astype(jnp.float32)
        shift = b.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    return x * scale.astype(x.dtype).reshape(shape) + \
        shift.astype(x.dtype).reshape(shape)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", name=None):
    """Returns (out, new_running_mean, new_running_var). The Layer writes the
    running stats back (stateless-functional twist on the reference's
    in-place MomentumTensor update)."""
    def impl(x, rm, rv, *wb, training, momentum, epsilon, data_format):
        if data_format in ("NCHW", "NCL", "NCDHW") and x.ndim > 2:
            axes = (0,) + tuple(range(2, x.ndim))
            shape = (1, -1) + (1,) * (x.ndim - 2)
        else:
            axes = tuple(range(x.ndim - 1))
            shape = (1,) * (x.ndim - 1) + (-1,)
        if training:
            # batch stats in f32 via the shared one-pass moments (see
            # _one_pass_moments: single read, cancellation-guarded);
            # running stats stay in the buffer dtype
            mean, var = _one_pass_moments(x, axes)
            new_rm = momentum * rm + (1 - momentum) * mean.astype(rm.dtype)
            new_rv = momentum * rv + (1 - momentum) * var.astype(rv.dtype)
        else:
            mean, var = rm, rv
            new_rm, new_rv = rm, rv
        w, b = wb if wb else (None, None)
        out = _fold_scale_shift(x, mean, var, w, b, epsilon, shape)
        return out, new_rm, new_rv

    from . import pallas as P
    if weight is not None and bias is None:
        # bias_attr=False layers: affine with weight only — substitute
        # zeros so both branches below keep their two-or-none contract
        w_arr = as_tensor(weight).data
        bias = jnp.zeros(w_arr.shape, w_arr.dtype)
    elif weight is None and bias is not None:
        # weight_attr=False: the symmetric case — ones for the scale,
        # else the real bias parameter would be silently dropped
        b_arr = as_tensor(bias).data
        weight = jnp.ones(b_arr.shape, b_arr.dtype)
    chan_last = not (data_format in ("NCHW", "NCL", "NCDHW") and
                     getattr(x, "ndim", 2) > 2)
    if training and weight is not None and chan_last and \
            P.enabled("batch_norm"):
        # fused Pallas path (channels-last only — a transpose around the
        # kernel would cost the pass it saves); running stats fold on top
        # of the kernel's (out, mean, var)
        from .pallas.batch_norm import bn_channels_last

        def impl_pl(x, rm, rv, w, b):
            out2, mean, var = bn_channels_last(x, w, b, epsilon)
            new_rm = momentum * rm + (1 - momentum) * mean.astype(rm.dtype)
            new_rv = momentum * rv + (1 - momentum) * var.astype(rv.dtype)
            return out2, new_rm, new_rv

        return apply(impl_pl,
                     (x, running_mean, running_var, weight, bias),
                     n_out=3, name="pallas_batch_norm")

    args = (x, running_mean, running_var)
    if weight is not None:
        args = args + (weight, bias)
    with _pscope("F.batch_norm"):
        out = apply(impl, args,
                    dict(training=training, momentum=momentum,
                         epsilon=epsilon, data_format=data_format),
                    n_out=3, name="batch_norm")
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    """reference: layer_norm_op fused CUDA kernel → here plain XLA (fused by
    the compiler); Pallas variant in ops/pallas/layer_norm.py for the
    flagship path."""
    ns = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(
        normalized_shape)
    naxes = len(ns)

    def impl(x, *wb, naxes, epsilon):
        axes = tuple(range(x.ndim - naxes, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) * lax.rsqrt(var + epsilon)
        if wb:
            w, b = wb
            out = out * w + b
        return out

    args = (x,) if weight is None else (x, weight, bias)
    with _pscope("F.layer_norm"):
        return apply(impl, args, dict(naxes=naxes, epsilon=epsilon),
                     name="layer_norm")


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    def impl(x, *wb, num_groups, epsilon, data_format):
        if data_format == "NHWC":
            x = jnp.moveaxis(x, -1, 1)
        n, c = x.shape[:2]
        sp = x.shape[2:]
        xg = x.reshape(n, num_groups, c // num_groups, *sp)
        axes = tuple(range(2, xg.ndim))
        mean, var = _one_pass_moments(xg, axes, keepdims=True)
        out = ((xg - mean) * lax.rsqrt(var + epsilon)).astype(
            x.dtype).reshape(x.shape)
        if wb:
            w, b = wb
            shape = (1, c) + (1,) * len(sp)
            out = out * w.reshape(shape) + b.reshape(shape)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x,) if weight is None else (x, weight, bias)
    return apply(impl, args, dict(num_groups=num_groups, epsilon=epsilon,
                                  data_format=data_format), name="group_norm")


def instance_norm(x, weight=None, bias=None, epsilon=1e-5, name=None):
    def impl(x, *wb, epsilon):
        axes = tuple(range(2, x.ndim))
        mean, var = _one_pass_moments(x, axes, keepdims=True)
        out = ((x - mean) * lax.rsqrt(var + epsilon)).astype(x.dtype)
        if wb:
            w, b = wb
            shape = (1, -1) + (1,) * (x.ndim - 2)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out
    args = (x,) if weight is None else (x, weight, bias)
    return apply(impl, args, dict(epsilon=epsilon), name="instance_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(x, p, axis, epsilon):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                                keepdims=True), 1.0 / p)
        return x / jnp.maximum(nrm, epsilon)
    return apply(impl, (x,), dict(p=p, axis=axis, epsilon=epsilon),
                 name="normalize")


def local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0, name=None):
    """reference: lrn_op.cc (NCHW)."""
    def impl(x, size, alpha, beta, k):
        sq = jnp.square(x)
        half = size // 2
        pads = [(0, 0), (half, size - 1 - half), (0, 0), (0, 0)]
        sq = jnp.pad(sq, pads)
        acc = sum(sq[:, i:i + x.shape[1]] for i in range(size))
        return x / jnp.power(k + alpha * acc, beta)
    return apply(impl, (x,), dict(size=size, alpha=alpha, beta=beta, k=k),
                 name="lrn")


# ---------------------------------------------------------------------------
# dropout (reference: dropout_op.cu) — global threaded PRNG

def dropout(x, p=0.5, training=True, mode="upscale_in_train", axis=None,
            name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda x, p: x * (1 - p), (x,), dict(p=p),
                         name="dropout_infer")
        return x
    key = prandom.next_key_graph()  # symbolic per-run key in static mode

    def impl(x, key, p, mode, axis):
        shape = x.shape
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, x / (1.0 - p), 0.0)
        return jnp.where(keep, x, 0.0)

    return apply(impl, (x, key), dict(p=p, mode=mode, axis=axis),
                 name="dropout")


# ---------------------------------------------------------------------------
# attention / misc

def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None,
                                 name=None):
    """Plain XLA attention (B, H, S, D). Flash/pallas variant in
    ops/pallas/flash_attention.py; ring variant in parallel/ring_attention."""
    p_drop = float(dropout_p) if training else 0.0
    attrs = dict(is_causal=is_causal, scale=scale)

    def impl(q, k, v, *rest, is_causal, scale):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        logits = jnp.einsum("...qd,...kd->...qk", q, k) * s
        if attn_mask is not None:
            m = rest[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, -1e9)
            else:
                logits = logits + m
        if is_causal:
            sq, sk = logits.shape[-2:]
            causal = jnp.tril(jnp.ones((sq, sk), jnp.bool_))
            logits = jnp.where(causal, logits, -1e9)
        probs = jax.nn.softmax(logits, axis=-1)
        if p_drop > 0.0:
            # dropout on the attention PROBABILITIES (reference semantics:
            # the attn_dropout in multihead attention / what the fused
            # Pallas kernel does in-kernel), not on the context output
            keep = jax.random.bernoulli(rest[-1], 1.0 - p_drop,
                                        probs.shape)
            probs = jnp.where(keep, probs / (1.0 - p_drop), 0.0)
        return jnp.einsum("...qk,...kd->...qd", probs, v)

    args = (q, k, v)
    if attn_mask is not None:
        args = args + (attn_mask,)
    if p_drop > 0.0:
        args = args + (prandom.next_key_graph(),)
    return apply(impl, args, attrs, name="sdpa")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW", name=None):
    """reference: interpolate_op.cc (nearest/bilinear)."""
    def impl(x, size, scale_factor, mode, align_corners, data_format):
        chan_last = data_format == "NHWC"
        if not chan_last:
            x = jnp.moveaxis(x, 1, -1)
        n, h, w, c = x.shape
        if size is None:
            sf = _pair(scale_factor, 2)
            size = (int(h * sf[0]), int(w * sf[1]))
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "bicubic": "cubic"}[mode]
        out = jax.image.resize(x, (n, size[0], size[1], c), method=method)
        if not chan_last:
            out = jnp.moveaxis(out, -1, 1)
        return out
    sz = tuple(size) if isinstance(size, (list, tuple)) else size
    return apply(impl, (x,), dict(size=sz, scale_factor=scale_factor,
                                  mode=mode, align_corners=align_corners,
                                  data_format=data_format),
                 name="interpolate")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    def impl(x, r, data_format):
        if data_format == "NCHW":
            n, c, h, w = x.shape
            x = x.reshape(n, c // (r * r), r, r, h, w)
            x = x.transpose(0, 1, 4, 2, 5, 3)
            return x.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = x.shape
        x = x.reshape(n, h, w, r, r, c // (r * r))
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h * r, w * r, c // (r * r))
    return apply(impl, (x,), dict(r=upscale_factor, data_format=data_format),
                 name="pixel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """reference: unfold_op.cc (im2col)."""
    def impl(x, kernel_sizes, strides, paddings, dilations):
        k = _pair(kernel_sizes, 2)
        s = _pair(strides, 2)
        p = _norm_padding(paddings, 2)
        d = _pair(dilations, 2)
        patches = lax.conv_general_dilated_patches(
            x, filter_shape=k, window_strides=s, padding=p, rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n, ckk, oh, ow = patches.shape
        return patches.reshape(n, ckk, oh * ow)
    return apply(impl, (x,), dict(kernel_sizes=kernel_sizes, strides=strides,
                                  paddings=paddings, dilations=dilations),
                 name="unfold")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """reference: label_smooth_op.cc"""
    def impl(label, epsilon):
        k = label.shape[-1]
        return (1 - epsilon) * label + epsilon / k
    return apply(impl, (label,), dict(epsilon=epsilon), name="label_smooth")
