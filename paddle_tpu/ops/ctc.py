"""paddle_tpu.ops.ctc — CTC loss and decoding.

TPU-native rebuild of the reference's CTC stack
(reference: paddle/fluid/operators/warpctc_op.cc — which wraps the warpctc
CUDA library — and fluid/layers/nn.py:ctc_greedy_decoder, layers/loss.py:
warpctc).

Redesign: the warpctc library is a GPU-side ragged kernel; on TPU the CTC
forward-backward is expressed directly as a ``lax.scan`` over time on the
log-alpha lattice of the padded extended label sequence ([B, 2L+1]),
batched over sequences — XLA fuses the whole recurrence, and the gradient
is jax autodiff of the forward pass (which equals the classic
forward-backward gradient). No ragged tensors: inputs are padded
``[B, T, C]`` logits + per-sequence input/label lengths, the layout TPU
wants anyway.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import as_tensor
from ..dispatch import apply

NEG_INF = -1e30


def _ctc_nll(log_probs, labels, input_len, label_len, blank):
    """log_probs: [B, T, C] (log-softmaxed), labels: [B, L] int,
    returns nll [B] (fp32)."""
    b, t, c = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1

    labels = labels.astype(jnp.int32)
    input_len = input_len.astype(jnp.int32)
    label_len = label_len.astype(jnp.int32)

    # extended sequence: blank, y0, blank, y1, ..., blank
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    pos = jnp.arange(s)[None, :]
    in_ext = pos < (2 * label_len + 1)[:, None]

    # "can skip" from s-2: ext[s] is a label and differs from ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :s]
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(lp_t, idx):
        # lp_t: [B, C] -> [B, S] log-prob of each extended symbol
        return jnp.take_along_axis(lp_t, idx, axis=1)

    lp0 = emit(log_probs[:, 0], ext)
    alpha0 = jnp.full((b, s), NEG_INF, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(lp0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_len > 0, lp0[:, 1],
                                           NEG_INF))

    def step(alpha, inp):
        lp_t, tstep = inp
        a_prev = alpha
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                       constant_values=NEG_INF)[:, :s]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                       constant_values=NEG_INF)[:, :s]
        a_m2 = jnp.where(can_skip, a_m2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_m1), a_m2)
        new = merged + emit(lp_t, ext)
        new = jnp.where(in_ext, new, NEG_INF)
        keep = (tstep < input_len)[:, None]
        return jnp.where(keep, new, alpha), None

    alpha, _ = jax.lax.scan(
        step, alpha0,
        (jnp.moveaxis(log_probs[:, 1:], 1, 0), jnp.arange(1, t)))

    # total = logaddexp(alpha[2*label_len], alpha[2*label_len - 1])
    idx_last = (2 * label_len)[:, None]
    idx_prev = jnp.maximum(2 * label_len - 1, 0)[:, None]
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
    a_prev = jnp.where(label_len > 0, a_prev, NEG_INF)
    return -jnp.logaddexp(a_last, a_prev)


def ctc_loss(logits, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss over padded batches (paddle.nn.functional.ctc_loss /
    reference warpctc semantics, TPU formulation).

    logits: [B, T, C] UNnormalized; labels: [B, L] int (padded);
    input_lengths/label_lengths: [B]."""
    def impl(logits, labels, ilen, llen, blank, reduction, norm_by_times):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = _ctc_nll(lp, labels, ilen, llen, blank)
        if norm_by_times:
            nll = nll / jnp.maximum(ilen.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # torch/paddle 'mean': per-sample loss / label_len, then mean
            return jnp.mean(nll / jnp.maximum(
                llen.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply(impl,
                 (logits, as_tensor(labels), as_tensor(input_lengths),
                  as_tensor(label_lengths)),
                 dict(blank=blank, reduction=reduction,
                      norm_by_times=norm_by_times),
                 name="ctc_loss")


def warpctc(input, label, input_length=None, label_length=None, blank=0,
            norm_by_times=False, name=None):
    """reference: fluid/layers/loss.py:499 warpctc — returns the
    per-sequence loss [B, 1] (no reduction)."""
    x = as_tensor(input)
    t = x.shape[1] if x.ndim == 3 else None
    if input_length is None:
        b = x.shape[0]
        input_length = np.full((b,), t, np.int32)
    if label_length is None:
        # valid labels can never equal blank in CTC, so both the usual
        # 0-padded batches (blank=0) and -1-padded batches count correctly
        lab = np.asarray(jax.device_get(as_tensor(label).data))
        label_length = ((lab >= 0) & (lab != blank)).sum(-1).astype(
            np.int32)
    out = ctc_loss(x, label, input_length, label_length, blank=blank,
                   reduction="none", norm_by_times=norm_by_times)
    from .manip import unsqueeze
    return unsqueeze(out, -1)


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=-1,
                       name=None):
    """reference: fluid/layers/nn.py:5115 ctc_greedy_decoder — argmax per
    step, merge repeats, drop blanks. Padded formulation: returns
    (decoded [B, T] padded with `padding_value`, out_lengths [B])."""
    def impl(x, *maybe_len, blank, padding_value):
        b, t, c = x.shape
        ln = maybe_len[0].astype(jnp.int32) if maybe_len else jnp.full(
            (b,), t, jnp.int32)
        best = jnp.argmax(x, axis=-1).astype(jnp.int32)    # [B, T]
        prev = jnp.pad(best, ((0, 0), (1, 0)), constant_values=-1)[:, :t]
        valid = (jnp.arange(t)[None, :] < ln[:, None])
        keep = (best != blank) & (best != prev) & valid

        # stable compaction: target position = cumsum(keep) - 1
        tgt = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        out_len = jnp.max(jnp.where(keep, tgt + 1, 0), axis=1)

        # scatter kept symbols to compacted slots; slot t is the discard
        # bin for dropped steps (trimmed off)
        def compact(row_best, row_keep, row_tgt):
            buf = jnp.full((t + 1,), padding_value, jnp.int32)
            idx = jnp.where(row_keep, row_tgt, t)
            return buf.at[idx].set(jnp.where(row_keep, row_best,
                                             padding_value))[:t]

        decoded = jax.vmap(compact)(best, keep, tgt)
        return decoded, out_len

    args = (input,) if input_length is None else (input,
                                                  as_tensor(input_length))
    return apply(impl, args, dict(blank=blank, padding_value=padding_value),
                 nondiff=True, n_out=2, name="ctc_greedy_decoder")
