"""paddle_tpu.ops.pallas — hand-fused TPU kernels.

TPU-native rebuild of the reference's fused CUDA kernels
(reference: paddle/fluid/operators/fused/fused_elemwise_activation_op.cu,
layer_norm_op.cu, softmax_with_cross_entropy_op.cu, optimizers/adam_op.cu
multi-tensor path). Each kernel runs compiled on TPU and in interpret mode
on CPU (tests), and exposes a custom VJP so the tape/jit path differentiates
through it.
"""
import jax


def interpret_mode():
    """Pallas interpret=True off-TPU (CPU tests); compiled on TPU."""
    return jax.default_backend() not in ("tpu",) and not any(
        d.platform in ("tpu", "axon") for d in jax.devices())


def on_tpu():
    """True when the compiled-kernel path is live. Layers use this to
    auto-enable Pallas kernels on TPU while keeping CPU tests on the
    (fast) XLA path; interpret-mode tests opt in via force flags."""
    return not interpret_mode()


# Per-kernel default overrides: None = auto (on on TPU, off elsewhere).
# bench.py probes each kernel on the live device and disables just the
# ones that fail to compile, instead of losing the whole run.
_overrides = {}
_KERNELS = ("layer_norm", "fused_adam", "flash_attention", "softmax_xent")


def configure(**kernels):
    """configure(layer_norm=False, fused_adam=None, ...) — override the
    auto default for named kernels ('layer_norm', 'fused_adam',
    'flash_attention', 'softmax_xent'). None restores auto.

    The flag is read when an op traces, so call configure() BEFORE the
    first jitted step — a step already compiled keeps the kernel choice
    it was traced with."""
    for k, v in kernels.items():
        if k not in _KERNELS:
            raise ValueError(
                f"unknown pallas kernel {k!r}; known: {_KERNELS}")
        if v is None:
            _overrides.pop(k, None)
        else:
            _overrides[k] = bool(v)


def enabled(kernel):
    """Effective default for one kernel, honoring configure() overrides."""
    v = _overrides.get(kernel)
    return on_tpu() if v is None else v


from . import layer_norm as layer_norm_mod
from . import softmax_xent as softmax_xent_mod
from . import flash_attention as flash_attention_mod
from . import fused_adam as fused_adam_mod

from .layer_norm import layer_norm
from .softmax_xent import softmax_cross_entropy
from .flash_attention import flash_attention
from .fused_adam import fused_adam_update
