"""paddle_tpu.ops.pallas — hand-fused TPU kernels.

TPU-native rebuild of the reference's fused CUDA kernels
(reference: paddle/fluid/operators/fused/fused_elemwise_activation_op.cu,
layer_norm_op.cu, softmax_with_cross_entropy_op.cu, optimizers/adam_op.cu
multi-tensor path). Each kernel runs compiled on TPU and in interpret mode
on CPU (tests), and exposes a custom VJP so the tape/jit path differentiates
through it.
"""
import jax


def interpret_mode():
    """Pallas interpret=True off-TPU (CPU tests); compiled on TPU."""
    return jax.default_backend() not in ("tpu",) and not any(
        d.platform in ("tpu", "axon") for d in jax.devices())


def on_tpu():
    """True when the compiled-kernel path is live. Layers use this to
    auto-enable Pallas kernels on TPU while keeping CPU tests on the
    (fast) XLA path; interpret-mode tests opt in via force flags."""
    return not interpret_mode()


# Per-kernel default overrides: None = auto. bench.py probes each kernel
# on the live device and disables just the ones that fail to compile,
# instead of losing the whole run.
_overrides = {}
_KERNELS = ("layer_norm", "fused_adam", "fused_adam_multi",
            "flash_attention", "softmax_xent", "batch_norm")

# Measured auto defaults (v5e, BERT-base ablation, docs/perf_r04.md):
# layer_norm is the only unconditional win (+0.4%); fused_adam loses
# 13.6% to XLA's own update fusion (a separate pallas dispatch per param
# tensor vs one fused backward+update program); softmax_xent loses 1.7%
# at seq-128 shapes (its value is the O(N·V) HBM saving, opt-in);
# flash_attention wins only once S^2 scores dominate — seq-gated via
# _flash_min_seq below. configure(kernel=True/False) still forces any
# of them either way.
# batch_norm: built to attack the ResNet trace's BN-bound 70% (see
# docs/perf_r04.md), auto-off until scripts/bench_pallas_bn.py proves it
# beats the (already once-fixed) XLA schedule on the chip.
# fused_adam_multi: ONE dispatch over concatenated buffers (r5; the
# r4-measured -13.6% was the per-tensor dispatch) — auto-off until
# scripts/bench_adam_multi.py proves it beats XLA's own update fusion.
_AUTO_ON = {"layer_norm": True, "flash_attention": True,
            "fused_adam": False, "fused_adam_multi": False,
            "softmax_xent": False, "batch_norm": False}


# flash is an O(S^2)-score win: below some sequence length the XLA sdpa
# (one fused attention) beats the blocked kernel's overheads. Measured
# on v5e (scripts/tune_flash.py + ablate_bert.py, docs/perf_r04.md):
# seq 128 flash loses 11% full-model; seq 512 is a wash (flash ahead
# ~5% kernel-only, and O(S) memory tiebreaks); seq 2048 flash wins
# 1.53x kernel-only. Crossover set at 512; 0 = flash whenever enabled.
_FLASH_MIN_SEQ_DEFAULT = 512
_flash_min_seq = _FLASH_MIN_SEQ_DEFAULT
_UNSET = object()


def configure(flash_min_seq=_UNSET, **kernels):
    """configure(layer_norm=False, fused_adam=None, ...) — override the
    auto default for named kernels ('layer_norm', 'fused_adam',
    'flash_attention', 'softmax_xent', 'batch_norm'). None restores
    auto.
    flash_min_seq=N routes sequences shorter than N to XLA sdpa even
    with the flash kernel enabled (N=0 disables the gate);
    flash_min_seq=None restores the measured default crossover,
    matching the kernel knobs' None-resets semantics.

    The flag is read when an op traces, so call configure() BEFORE the
    first jitted step — a step already compiled keeps the kernel choice
    it was traced with."""
    global _flash_min_seq
    if flash_min_seq is not _UNSET:
        _flash_min_seq = _FLASH_MIN_SEQ_DEFAULT \
            if flash_min_seq is None else int(flash_min_seq)
    for k, v in kernels.items():
        if k not in _KERNELS:
            raise ValueError(
                f"unknown pallas kernel {k!r}; known: {_KERNELS}")
        if v is None:
            _overrides.pop(k, None)
        else:
            _overrides[k] = bool(v)


def enabled(kernel, seq_len=None):
    """Effective default for one kernel, honoring configure() overrides
    (and the flash seq-length crossover when seq_len is given)."""
    if kernel not in _KERNELS:
        raise ValueError(
            f"unknown pallas kernel {kernel!r}; known: {_KERNELS}")
    v = _overrides.get(kernel)
    on = (on_tpu() and _AUTO_ON[kernel]) if v is None else v
    if on and kernel == "flash_attention" and seq_len is not None and \
            seq_len < _flash_min_seq:
        return False
    return on


from . import layer_norm as layer_norm_mod
from . import softmax_xent as softmax_xent_mod
from . import flash_attention as flash_attention_mod
from . import fused_adam as fused_adam_mod
from . import batch_norm as batch_norm_mod

from .layer_norm import layer_norm
from .softmax_xent import softmax_cross_entropy
from .flash_attention import flash_attention
from .fused_adam import fused_adam_update
from .batch_norm import fused_batch_norm_train
