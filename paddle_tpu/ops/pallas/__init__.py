"""paddle_tpu.ops.pallas — hand-fused TPU kernels.

TPU-native rebuild of the reference's fused CUDA kernels
(reference: paddle/fluid/operators/fused/fused_elemwise_activation_op.cu,
layer_norm_op.cu, softmax_with_cross_entropy_op.cu, optimizers/adam_op.cu
multi-tensor path). Each kernel runs compiled on TPU and in interpret mode
on CPU (tests), and exposes a custom VJP so the tape/jit path differentiates
through it.
"""
import jax


def interpret_mode():
    """Pallas interpret=True off-TPU (CPU tests); compiled on TPU."""
    return jax.default_backend() not in ("tpu",) and not any(
        d.platform in ("tpu", "axon") for d in jax.devices())


def on_tpu():
    """True when the compiled-kernel path is live. Layers use this to
    auto-enable Pallas kernels on TPU while keeping CPU tests on the
    (fast) XLA path; interpret-mode tests opt in via force flags."""
    return not interpret_mode()


from . import layer_norm as layer_norm_mod
from . import softmax_xent as softmax_xent_mod
from . import flash_attention as flash_attention_mod
from . import fused_adam as fused_adam_mod

from .layer_norm import layer_norm
from .softmax_xent import softmax_cross_entropy
from .flash_attention import flash_attention
from .fused_adam import fused_adam_update
