"""paddle_tpu.ops.pallas — hand-fused TPU kernels.

TPU-native rebuild of the reference's fused CUDA kernels
(reference: paddle/fluid/operators/fused/fused_elemwise_activation_op.cu,
layer_norm_op.cu, softmax_with_cross_entropy_op.cu, optimizers/adam_op.cu
multi-tensor path). Each kernel runs compiled on TPU and in interpret mode
on CPU (tests), and exposes a custom VJP so the tape/jit path differentiates
through it.
"""
import jax


def interpret_mode():
    """Pallas interpret=True off-TPU (CPU tests); compiled on TPU."""
    return jax.default_backend() not in ("tpu",) and not any(
        d.platform in ("tpu", "axon") for d in jax.devices())


def on_tpu():
    """True when the compiled-kernel path is live. Layers use this to
    auto-enable Pallas kernels on TPU while keeping CPU tests on the
    (fast) XLA path; interpret-mode tests opt in via force flags."""
    return not interpret_mode()


# Per-kernel default overrides: None = auto (on on TPU, off elsewhere).
# bench.py probes each kernel on the live device and disables just the
# ones that fail to compile, instead of losing the whole run.
_overrides = {}
_KERNELS = ("layer_norm", "fused_adam", "flash_attention", "softmax_xent")


# flash is an O(S^2)-score win: below some sequence length the XLA sdpa
# (one fused attention) can beat the blocked kernel's overheads — the
# crossover is measured by scripts/ablate_bert.py and set here (0 = flash
# whenever enabled)
_flash_min_seq = 0
_UNSET = object()


def configure(flash_min_seq=_UNSET, **kernels):
    """configure(layer_norm=False, fused_adam=None, ...) — override the
    auto default for named kernels ('layer_norm', 'fused_adam',
    'flash_attention', 'softmax_xent'). None restores auto.
    flash_min_seq=N routes sequences shorter than N to XLA sdpa even
    with the flash kernel enabled (the ablation-tuned crossover);
    flash_min_seq=None restores the no-threshold default, matching the
    kernel knobs' None-resets semantics.

    The flag is read when an op traces, so call configure() BEFORE the
    first jitted step — a step already compiled keeps the kernel choice
    it was traced with."""
    global _flash_min_seq
    if flash_min_seq is not _UNSET:
        _flash_min_seq = 0 if flash_min_seq is None \
            else int(flash_min_seq)
    for k, v in kernels.items():
        if k not in _KERNELS:
            raise ValueError(
                f"unknown pallas kernel {k!r}; known: {_KERNELS}")
        if v is None:
            _overrides.pop(k, None)
        else:
            _overrides[k] = bool(v)


def enabled(kernel, seq_len=None):
    """Effective default for one kernel, honoring configure() overrides
    (and the flash seq-length crossover when seq_len is given)."""
    v = _overrides.get(kernel)
    on = on_tpu() if v is None else v
    if on and kernel == "flash_attention" and seq_len is not None and \
            seq_len < _flash_min_seq:
        return False
    return on


from . import layer_norm as layer_norm_mod
from . import softmax_xent as softmax_xent_mod
from . import flash_attention as flash_attention_mod
from . import fused_adam as fused_adam_mod

from .layer_norm import layer_norm
from .softmax_xent import softmax_cross_entropy
from .flash_attention import flash_attention
from .fused_adam import fused_adam_update
