"""Fused layer-norm Pallas kernel (reference: the fused CUDA
layer_norm_op.cu — one pass computing mean/var/normalize/affine).

Forward: grid over row-blocks; each block loads (BR, D) into VMEM, computes
row statistics on the VPU and writes the normalized affine output — one HBM
round-trip instead of the 4+ an unfused chain costs. Backward is a second
kernel producing dx exactly (the classic layernorm gradient) plus per-block
partial dw/db that are summed outside.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_rows(d, target=1 << 19):
    br = max(8, min(1024, target // max(d, 1)))
    return int(8 * max(1, br // 8))


# The bwd kernel holds 3 (BR, D) blocks double-buffered PLUS ~4 f32
# stack temporaries (x, g, xhat, dxhat); at 512K-element blocks that
# sits right at the 16MB scoped-VMEM edge — bf16 inputs fit, f32 inputs
# blew it on hardware at (8192, 768). 256K-element blocks (1MB f32)
# keep the worst case near ~10MB.
_BWD_TARGET = 1 << 18


def _fwd_kernel(x_ref, w_ref, b_ref, o_ref, mu_ref, rstd_ref, *, eps, d):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rstd
    o_ref[:] = (xhat * w_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mu_ref[:] = mu[:, 0][:, None]
    rstd_ref[:] = rstd[:, 0][:, None]


def _bwd_kernel(x_ref, w_ref, mu_ref, rstd_ref, g_ref, dx_ref, dw_ref,
                db_ref, *, d, n, br):
    # mask rows past n: the padding of a partial final block must not
    # poison the dw/db partial sums (OOB reads are NaN in interpret mode)
    i = pl.program_id(0)
    rows = jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0) + i * br
    valid = rows < n
    x = jnp.where(valid, x_ref[:].astype(jnp.float32), 0.0)
    g = jnp.where(valid, g_ref[:].astype(jnp.float32), 0.0)
    w = w_ref[:].astype(jnp.float32)
    mu = jnp.where(valid, mu_ref[:], 0.0)
    rstd = jnp.where(valid, rstd_ref[:], 0.0)
    xhat = (x - mu) * rstd
    dxhat = g * w
    m1 = jnp.mean(dxhat, axis=1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)
    # dw/db accumulate into ONE (1, d) output block revisited by every
    # grid step — TPU grids run sequentially, so += is a sound reduction
    @pl.when(i == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
    dw_ref[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(g, axis=0, keepdims=True)


def _run_fwd(x2, w, b, eps):
    from . import interpret_mode
    n, d = x2.shape
    br = _block_rows(d)
    grid = (pl.cdiv(n, br),)
    out, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, d=d),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2, w.reshape(1, d), b.reshape(1, d))
    return out, mu, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm2(x2, w, b, eps):
    out, _, _ = _run_fwd(x2, w, b, eps)
    return out


def _ln_fwd(x2, w, b, eps):
    out, mu, rstd = _run_fwd(x2, w, b, eps)
    return out, (x2, w, mu, rstd)


def _ln_bwd(eps, res, g):
    from . import interpret_mode
    x2, w, mu, rstd = res
    n, d = x2.shape
    br = _block_rows(d, _BWD_TARGET)
    nblocks = pl.cdiv(n, br)
    dx, dw_part, db_part = pl.pallas_call(
        functools.partial(_bwd_kernel, d=d, n=n, br=br),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2, w.reshape(1, d), mu, rstd, g)
    dw = dw_part[0].astype(w.dtype)
    db = db_part[0].astype(w.dtype)
    return dx, dw, db


_layer_norm2.defvjp(_ln_fwd, _ln_bwd)


def layer_norm(x, weight, bias, epsilon=1e-5):
    """Framework op: fused layer norm over the LAST axis. Accepts Tensors
    or arrays; differentiable through the tape and under jit."""
    from ...dispatch import apply

    def impl(x, w, b):
        d = x.shape[-1]
        lead = x.shape[:-1]
        x2 = x.reshape(-1, d)
        out = _layer_norm2(x2, w, b, epsilon)
        return out.reshape(*lead, d)

    return apply(impl, (x, weight, bias), name="pallas_layer_norm")
