"""Fused Adam Pallas kernel (reference: adam_op.cu — the fused/multi-tensor
update path FusedAdamKernel).

One kernel updates param, m, v in place (input_output_aliases) per tensor:
param/m/v stream HBM→VMEM once each and back once, with the whole update
arithmetic fused — matching what the reference needed a dedicated CUDA
kernel for. Scalars (lr, beta-pows) ride in SMEM.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _adam_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref,
                 vo_ref, *, beta1, beta2, eps):
    lr = scal_ref[0]
    b1p = scal_ref[1]
    b2p = scal_ref[2]
    g = g_ref[:].astype(jnp.float32)
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    mhat = m / (1.0 - b1p)
    vhat = v / (1.0 - b2p)
    po_ref[:] = (p_ref[:].astype(jnp.float32) -
                 lr * mhat / (jnp.sqrt(vhat) + eps)).astype(po_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_update(p, g, m, v, lr, beta1_pow, beta2_pow, beta1=0.9,
                      beta2=0.999, eps=1e-8):
    """Single-tensor fused update: returns (new_p, new_m, new_v).
    Called by optimizer.Adam when use_fused=True (arrays already flat or
    any-shaped; kernel sees a flattened 2D view)."""
    from . import interpret_mode
    shape = p.shape
    n = int(np.prod(shape)) if shape else 1
    # pad to a (rows, 128) layout
    cols = 128
    rows = -(-n // cols)
    pad = rows * cols - n

    def flat(x, dtype=jnp.float32):
        x = x.reshape(-1).astype(dtype)
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), dtype)])
        return x.reshape(rows, cols)

    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(beta1_pow, jnp.float32),
                      jnp.asarray(beta2_pow, jnp.float32)])

    # 7 VMEM refs (4 in + 3 out) × br×128×4B × 2 (double-buffer) must stay
    # under the ~16MB scoped-VMEM limit: br=1024 → 7MB. 4096 OOMs on v5e.
    br = min(rows, 1024)
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=beta1, beta2=beta2, eps=eps),
        grid=(pl.cdiv(rows, br),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), p.dtype),
            jax.ShapeDtypeStruct((rows, cols), jnp.float32),
            jax.ShapeDtypeStruct((rows, cols), jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret_mode(),
    )(scal, flat(p, p.dtype), flat(g), flat(m), flat(v))

    def unflat(x, dtype):
        x = x.reshape(-1)[:n].reshape(shape)
        return x.astype(dtype)

    return (unflat(new_p, p.dtype), unflat(new_m, jnp.float32),
            unflat(new_v, jnp.float32))


def _adam_multi_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, po_ref,
                       mo_ref, vo_ref, *, beta1, beta2, eps):
    lr = scal_ref[0]
    b1p = scal_ref[1]
    b2p = scal_ref[2]
    wd = scal_ref[3]
    g = g_ref[:]
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    mhat = m / (1.0 - b1p)
    vhat = v / (1.0 - b2p)
    p = p_ref[:]
    po_ref[:] = p - lr * mhat / (jnp.sqrt(vhat) + eps) - (lr * wd) * p
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_update_multi(ps, gs, ms, vs, lr, beta1_pow, beta2_pow,
                            beta1=0.9, beta2=0.999, eps=1e-8,
                            weight_decay=0.0):
    """Multi-tensor fused update (reference: adam_op.cu's multi-tensor
    FusedAdamKernel intent): ONE Pallas dispatch over every parameter,
    via flattened+concatenated f32 buffers, instead of one dispatch per
    tensor. Decoupled weight decay (AdamW) folds into the same pass.

    Layout note: the concat offsets are python-side values derived from
    static shapes, so they are "built once per trace" — jit.to_static's
    structure-version cache already guarantees a retrace (and thus a
    new layout) only when the param set changes.

    Semantics note: beta-pow bias correction is SHARED across tensors
    (the reference's multi-tensor kernel also carries one beta1_pow/
    beta2_pow). Identical to per-tensor updates whenever all params
    step together — the SPMD/jit training reality; per-tensor pows that
    diverged via selective freezing are not representable here.

    Returns (new_ps, new_ms, new_vs) with original shapes/dtypes."""
    from . import interpret_mode
    cols = 128
    sizes = [int(np.prod(p.shape)) if p.shape else 1 for p in ps]
    rows_each = [-(-n // cols) for n in sizes]  # per-tensor row padding
    offsets = np.cumsum([0] + rows_each)
    rows = int(offsets[-1])

    def flat_cat(xs, dtype=jnp.float32):
        parts = []
        for x, n, r in zip(xs, sizes, rows_each):
            x = x.reshape(-1).astype(dtype)
            pad = r * cols - n
            if pad:
                x = jnp.concatenate([x, jnp.zeros((pad,), dtype)])
            parts.append(x.reshape(r, cols))
        return jnp.concatenate(parts, axis=0)

    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(beta1_pow, jnp.float32),
                      jnp.asarray(beta2_pow, jnp.float32),
                      jnp.asarray(weight_decay, jnp.float32)])

    br = min(rows, 1024)  # same scoped-VMEM budget as the single path
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_multi_kernel, beta1=beta1, beta2=beta2,
                          eps=eps),
        grid=(pl.cdiv(rows, br),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)] * 4,
        out_specs=[pl.BlockSpec((br, cols), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)] * 3,
        out_shape=[jax.ShapeDtypeStruct((rows, cols), jnp.float32)] * 3,
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret_mode(),
    )(scal, flat_cat(ps), flat_cat(gs), flat_cat(ms), flat_cat(vs))

    def split(buf, refs, dtype_from=None):
        outs = []
        for i, (n, x) in enumerate(zip(sizes, refs)):
            seg = buf[offsets[i]:offsets[i + 1]].reshape(-1)[:n]
            outs.append(seg.reshape(x.shape).astype(
                x.dtype if dtype_from else jnp.float32))
        return outs

    return (split(new_p, ps, dtype_from=True), split(new_m, ms),
            split(new_v, vs))


def adam_step(p, g, m, v, lr, beta1_pow, beta2_pow, *, beta1=0.9,
              beta2=0.999, eps=1e-8, use_fused=None):
    """THE Adam update rule, shared by optimizer.Adam and the fleet/
    megatron SPMD step: the fused Pallas kernel when pallas.enabled
    ('fused_adam') (or use_fused forces it), else the identical plain-XLA
    math. Returns (new_p, new_m, new_v)."""
    if use_fused is None:
        from . import enabled
        use_fused = enabled("fused_adam")
    if use_fused:
        return fused_adam_update(p, g, m, v, lr, beta1_pow, beta2_pow,
                                 beta1=beta1, beta2=beta2, eps=eps)
    new_m = beta1 * m + (1 - beta1) * g
    new_v = beta2 * v + (1 - beta2) * g * g
    mhat = new_m / (1 - beta1_pow)
    vhat = new_v / (1 - beta2_pow)
    # cast back to the param dtype: the f32 strong-typed lr would
    # otherwise silently promote a bf16 param to f32 after one step
    # (dtype drift = a state-shape recompile); the fused kernel above
    # already preserves it via unflat
    new_p = (p - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
    return new_p, new_m, new_v


def fused_adam_update_flat(p, g, m, v, lr, beta1_pow, beta2_pow,
                           beta1=0.9, beta2=0.999, eps=1e-8,
                           weight_decay=0.0):
    """Fused kernel over an arena-flat 1-D buffer. The arena pads every
    group to an (8, 128)-tile multiple, so the (rows, 128) kernel view
    is a FREE reshape — no pad, no concat, unlike the multi-tensor path
    that rebuilds its concatenated layout every call."""
    from . import interpret_mode
    n = p.shape[0]
    cols = 128
    assert n % cols == 0, "arena buffers are 128-lane aligned"
    rows = n // cols

    def tile(x):
        return x.astype(jnp.float32).reshape(rows, cols)

    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(beta1_pow, jnp.float32),
                      jnp.asarray(beta2_pow, jnp.float32),
                      jnp.asarray(weight_decay, jnp.float32)])
    br = min(rows, 1024)  # same scoped-VMEM budget as the multi path
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_multi_kernel, beta1=beta1, beta2=beta2,
                          eps=eps),
        grid=(pl.cdiv(rows, br),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [
            pl.BlockSpec((br, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)] * 4,
        out_specs=[pl.BlockSpec((br, cols), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)] * 3,
        out_shape=[jax.ShapeDtypeStruct((rows, cols), jnp.float32)] * 3,
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret_mode(),
    )(scal, tile(p), tile(g), tile(m), tile(v))
    return (new_p.reshape(-1).astype(p.dtype),
            new_m.reshape(-1).astype(m.dtype),
            new_v.reshape(-1).astype(v.dtype))


def adam_step_flat(p, g, m, v, lr, beta1_pow, beta2_pow, *, beta1=0.9,
                   beta2=0.999, eps=1e-8, weight_decay=0.0, mask=None,
                   use_fused=None):
    """The Adam/AdamW update over arena-flat 1-D buffers — the same
    dispatch discipline as :func:`adam_step` (Pallas kernel when
    'fused_adam_multi' is enabled or ``use_fused`` forces it, identical
    plain-XLA math otherwise). The pure path's cast sequencing matches
    the per-leaf rule exactly — ``astype(p.dtype)`` after the adam term
    and again after the decoupled decay — so arena mode is bit-identical
    per element to the per-leaf update it replaces. ``mask`` (bool [n])
    freezes elements of members that produced no grad this step."""
    if use_fused is None:
        from . import enabled
        use_fused = enabled("fused_adam_multi")
    if use_fused and mask is None and p.dtype == jnp.float32:
        new_p, new_m, new_v = fused_adam_update_flat(
            p, g, m, v, lr, beta1_pow, beta2_pow, beta1=beta1,
            beta2=beta2, eps=eps, weight_decay=weight_decay)
        return new_p, new_m, new_v
    new_m = beta1 * m + (1 - beta1) * g
    new_v = beta2 * v + (1 - beta2) * g * g
    mhat = new_m / (1 - beta1_pow)
    vhat = new_v / (1 - beta2_pow)
    new_p = (p - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
    if weight_decay:
        new_p = (new_p - lr * weight_decay * p).astype(p.dtype)
    if mask is not None:
        new_p = jnp.where(mask, new_p, p)
        new_m = jnp.where(mask, new_m, m)
        new_v = jnp.where(mask, new_v, v)
    return new_p, new_m, new_v

