"""Fused softmax + cross-entropy Pallas kernel (reference: the fused CUDA
softmax_with_cross_entropy_op.cu).

One VMEM pass per row-block: row max, exp-sum, and the picked logit produce
the loss directly — the [N, V] softmax matrix is never materialized in HBM
on the forward pass. Backward recomputes softmax in-kernel and writes
(p - onehot) * g, again one pass.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_rows(v):
    target = 1 << 20
    br = max(8, min(512, target // max(v, 1)))
    return int(8 * max(1, br // 8))


def _fwd_kernel(logits_ref, labels_ref, loss_ref, *, v, eps):
    """eps>0 = uniform label smoothing folded into the same pass
    (reference: label_smooth + the soft path of
    softmax_with_cross_entropy_op, without materializing the (N, V)
    smoothed one-hot): loss = lse − (1−eps)·picked − (eps/V)·Σx."""
    x = logits_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    lse = jnp.log(jnp.sum(e, axis=1, keepdims=True)) + m
    labels = labels_ref[:]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = cols == labels
    picked = jnp.sum(jnp.where(onehot, x, 0.0), axis=1, keepdims=True)
    if eps:
        loss_ref[:] = (lse - (1.0 - eps) * picked -
                       (eps / v) * jnp.sum(x, axis=1, keepdims=True))
    else:
        loss_ref[:] = (lse - picked)


def _bwd_kernel(logits_ref, labels_ref, g_ref, dx_ref, *, v, eps):
    x = logits_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)
    labels = labels_ref[:]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == labels).astype(jnp.float32)
    if eps:
        target = (1.0 - eps) * onehot + (eps / v)
    else:
        target = onehot
    dx_ref[:] = ((p - target) * g_ref[:]).astype(dx_ref.dtype)


def _run(kernel, logits2, labels2, eps, extra=None, out_shape=None):
    from . import interpret_mode
    n, v = logits2.shape
    br = _block_rows(v)
    grid = (pl.cdiv(n, br),)
    in_specs = [
        pl.BlockSpec((br, v), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    args = [logits2, labels2]
    if extra is not None:
        in_specs.append(pl.BlockSpec((br, 1), lambda i: (i, 0),
                                     memory_space=pltpu.VMEM))
        args.append(extra)
    wide = out_shape[1] == v
    return pl.pallas_call(
        functools.partial(kernel, v=v, eps=eps),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, v) if wide else (br, 1),
                               lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            out_shape, logits2.dtype if wide else jnp.float32),
        interpret=interpret_mode(),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_xent2(logits2, labels2, eps=0.0):
    n, v = logits2.shape
    return _run(_fwd_kernel, logits2, labels2, eps, out_shape=(n, 1))


def _fwd(logits2, labels2, eps):
    loss = _softmax_xent2(logits2, labels2, eps)
    return loss, (logits2, labels2)


def _bwd(eps, res, g):
    logits2, labels2 = res
    n, v = logits2.shape
    dx = _run(_bwd_kernel, logits2, labels2, eps,
              extra=g.astype(jnp.float32), out_shape=(n, v))
    return dx, None


_softmax_xent2.defvjp(_fwd, _bwd)


def softmax_cross_entropy(logits, label, smooth_eps=0.0):
    """Framework op: fused per-position softmax cross-entropy with hard
    labels; returns loss with shape label.shape + (1,). smooth_eps>0 folds
    uniform label smoothing into the kernel (reference: label_smooth +
    softmax_with_cross_entropy(soft_label=True), without the (N, V)
    smoothed one-hot ever touching HBM)."""
    from ...dispatch import apply

    def impl(logits, label):
        v = logits.shape[-1]
        lead = logits.shape[:-1]
        l2 = logits.reshape(-1, v)
        lab2 = label.reshape(-1, 1).astype(jnp.int32)
        loss = _softmax_xent2(l2, lab2, float(smooth_eps))
        return loss.reshape(*lead, 1)

    return apply(impl, (logits, label), name="pallas_softmax_xent")
