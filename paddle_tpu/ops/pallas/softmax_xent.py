"""Fused softmax + cross-entropy Pallas kernel (reference: the fused CUDA
softmax_with_cross_entropy_op.cu).

Forward: one VMEM pass per row-block — row max, exp-sum, and the picked
logit produce the loss directly; the [N, V] softmax matrix is never
materialized in HBM. The per-row lse is saved as a residual, which makes
the backward purely elementwise (dx = (exp(x − lse) − target)·g): it
tiles over BOTH rows and vocab, so no kernel ever holds a full-width row
block in VMEM (the full-width variant blew the 16MB scoped-VMEM limit at
BERT shapes).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_rows(v):
    target = 1 << 20
    br = max(8, min(512, target // max(v, 1)))
    return int(8 * max(1, br // 8))


def _fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref, *, v, eps):
    """eps>0 = uniform label smoothing folded into the same pass
    (reference: label_smooth + the soft path of
    softmax_with_cross_entropy_op, without materializing the (N, V)
    smoothed one-hot): loss = lse − (1−eps)·picked − (eps/V)·Σx.

    Also emits the per-row lse as a residual: with it, the backward pass
    is purely elementwise (p = exp(x − lse)), so it tiles over BOTH rows
    and vocab instead of holding whole 30k-wide rows in VMEM (which blew
    the 16MB scoped-VMEM limit at BERT shapes)."""
    x = logits_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    lse = jnp.log(jnp.sum(e, axis=1, keepdims=True)) + m
    labels = labels_ref[:]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = cols == labels
    picked = jnp.sum(jnp.where(onehot, x, 0.0), axis=1, keepdims=True)
    if eps:
        loss_ref[:] = (lse - (1.0 - eps) * picked -
                       (eps / v) * jnp.sum(x, axis=1, keepdims=True))
    else:
        loss_ref[:] = (lse - picked)
    lse_ref[:] = lse


def _bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dx_ref, *, v, eps,
                bv):
    """Elementwise given the forward's lse: dx = (exp(x−lse) − target)·g.
    Grid is (row-blocks, vocab-blocks); each block sees only a (br, bv)
    logits tile, so VMEM stays bounded for any vocab size."""
    j = pl.program_id(1)
    x = logits_ref[:].astype(jnp.float32)
    p = jnp.exp(x - lse_ref[:])
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == labels_ref[:]).astype(jnp.float32)
    if eps:
        target = (1.0 - eps) * onehot + (eps / v)
    else:
        target = onehot
    valid = (cols < v).astype(jnp.float32)  # vocab-tail padding → 0
    dx_ref[:] = ((p - target) * g_ref[:] * valid).astype(dx_ref.dtype)


def _run_fwd(logits2, labels2, eps):
    from . import interpret_mode
    n, v = logits2.shape
    br = _block_rows(v)
    grid = (pl.cdiv(n, br),)
    narrow = pl.BlockSpec((br, 1), lambda i: (i, 0),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, v=v, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, v), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            narrow,
        ],
        out_specs=(narrow, narrow),
        out_shape=(jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        interpret=interpret_mode(),
    )(logits2, labels2)


def _run_bwd(logits2, labels2, lse, g, eps):
    from . import interpret_mode
    n, v = logits2.shape
    bv = min(v, 2048)
    # 128×2048 f32 = 1MB tiles: in+out double-buffered plus ~4 stack
    # temps ≈ 8MB — half the scoped-VMEM limit (the 2MB-tile variant
    # also passed on hardware, but with zero headroom)
    br = max(8, min(128, _block_rows(bv)))
    grid = (pl.cdiv(n, br), pl.cdiv(v, bv))
    narrow = pl.BlockSpec((br, 1), lambda i, j: (i, 0),
                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, v=v, eps=eps, bv=bv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            narrow, narrow, narrow,
        ],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, v), logits2.dtype),
        interpret=interpret_mode(),
    )(logits2, labels2, lse, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_xent2(logits2, labels2, eps=0.0):
    return _run_fwd(logits2, labels2, eps)[0]


def _fwd(logits2, labels2, eps):
    loss, lse = _run_fwd(logits2, labels2, eps)
    return loss, (logits2, labels2, lse)


def _bwd(eps, res, g):
    logits2, labels2, lse = res
    dx = _run_bwd(logits2, labels2, lse, g.astype(jnp.float32), eps)
    return dx, None


_softmax_xent2.defvjp(_fwd, _bwd)


def softmax_cross_entropy(logits, label, smooth_eps=0.0):
    """Framework op: fused per-position softmax cross-entropy with hard
    labels; returns loss with shape label.shape + (1,). smooth_eps>0 folds
    uniform label smoothing into the kernel (reference: label_smooth +
    softmax_with_cross_entropy(soft_label=True), without the (N, V)
    smoothed one-hot ever touching HBM)."""
    from ...dispatch import apply

    def impl(logits, label):
        v = logits.shape[-1]
        lead = logits.shape[:-1]
        l2 = logits.reshape(-1, v)
        lab2 = label.reshape(-1, 1).astype(jnp.int32)
        loss = _softmax_xent2(l2, lab2, float(smooth_eps))
        return loss.reshape(*lead, 1)

    return apply(impl, (logits, label), name="pallas_softmax_xent")
