"""Fused training-mode batch-norm Pallas kernels (reference: the fused
CUDA batch_norm_op.cu / sync_batch_norm_op.cu pair; here the single-chip
training path).

Channels-LAST only: x viewed as (M, C) rows with C on the lanes — the
natural layout for NHWC conv stacks, where the (N,H,W,C)→(M,C) view is
free. NCHW callers keep the XLA path (a transpose around the kernel would
cost the very HBM pass this kernel exists to save).

Pass structure (the HBM-traffic floor for batch norm):
  fwd: stats kernel reads x once, accumulating per-channel Σx and Σx² in
       f32 into (1, C) outputs revisited across the sequential TPU grid;
       normalize kernel reads x once more and writes y = x·scale + shift
       with the (1, C) scale/shift staged in VMEM.
  bwd: reduction kernel reads (x, g) once for dgamma = Σ g·x̂ and
       dbeta = Σ g; elementwise kernel reads (x, g) again and writes
       dx = (w·rstd)·(g − dbeta/M − x̂·dgamma/M).

Five array passes total — the same count a perfectly-fused XLA schedule
needs, but with the f32 converts, squares and x̂ recomputation kept in
registers instead of round-tripping f32 copies through HBM (the
`convert_reduce_fusion` cost the ResNet-50 trace showed at ~8 ms/step).

Default-OFF (`pallas.configure(batch_norm=True)` opts in): the fused_adam
lesson (13.6% LOSS vs XLA's own fusion, docs/perf_r04.md) is that
hand-written kernels must beat the compiler on the chip before they ride
the default path; scripts/bench_pallas_bn.py measures exactly that when
a chip window is available.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_rows(c, target=1 << 18):
    br = max(8, min(1024, target // max(c, 1)))
    return int(8 * max(1, br // 8))


def _stats_kernel(x_ref, c_ref, s_ref, s2_ref, *, m, br):
    """Accumulates Σ(x−c) and Σ(x−c)² with c = a per-channel sample
    (the same cancellation guard as the XLA path in nn_ops.batch_norm:
    raw Σx² at large mean loses the entire variance to f32 rounding;
    shifted, both accumulators stay O(σ²)-scaled)."""
    i = pl.program_id(0)
    rows = jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0) + i * br
    valid = rows < m  # padding rows of the final block must not pollute
    x = jnp.where(valid, x_ref[:].astype(jnp.float32) - c_ref[:], 0.0)

    @pl.when(i == 0)
    def _init():
        s_ref[:] = jnp.zeros_like(s_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    s_ref[:] += jnp.sum(x, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(x * x, axis=0, keepdims=True)


def _norm_kernel(x_ref, scale_ref, shift_ref, o_ref):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * scale_ref[:] +
                shift_ref[:]).astype(o_ref.dtype)


def _bwd_reduce_kernel(x_ref, g_ref, mean_ref, rstd_ref, dg_ref, db_ref,
                       *, m, br):
    i = pl.program_id(0)
    rows = jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0) + i * br
    valid = rows < m
    x = jnp.where(valid, x_ref[:].astype(jnp.float32), 0.0)
    g = jnp.where(valid, g_ref[:].astype(jnp.float32), 0.0)
    xhat = (x - mean_ref[:]) * rstd_ref[:]

    @pl.when(i == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dg_ref[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(g, axis=0, keepdims=True)


def _bwd_dx_kernel(x_ref, g_ref, mean_ref, rstd_ref, wr_ref, dgm_ref,
                   dbm_ref, gmv_ref, dx_ref):
    """dx = (w·rstd)·(g − dbeta/M − x̂·dgamma/M) + gm/M + (2/M)(x−mean)gv.
    dgm/dbm arrive pre-divided by M; gmv carries the (rarely nonzero)
    cotangents of the direct mean/var outputs, pre-scaled (gm/M stacked
    over 2gv/M), so consuming batch stats in a loss stays exact."""
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    xc = x - mean_ref[:]
    xhat = xc * rstd_ref[:]
    extra = gmv_ref[0:1, :] + xc * gmv_ref[1:2, :]
    dx_ref[:] = (wr_ref[:] * (g - dbm_ref[:] - xhat * dgm_ref[:]) + extra
                 ).astype(dx_ref.dtype)


def _row_specs(br, c, n_narrow):
    wide = pl.BlockSpec((br, c), lambda i: (i, 0), memory_space=pltpu.VMEM)
    narrow = pl.BlockSpec((1, c), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    return wide, [narrow] * n_narrow


def _stats(x2):
    from . import interpret_mode
    m, c = x2.shape
    br = _block_rows(c)
    wide, narrows = _row_specs(br, c, 1)
    narrow_out = pl.BlockSpec((1, c), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    shift = jax.lax.stop_gradient(x2[0:1].astype(jnp.float32))
    s, s2 = pl.pallas_call(
        functools.partial(_stats_kernel, m=m, br=br),
        grid=(pl.cdiv(m, br),),
        in_specs=[wide] + narrows,
        out_specs=(narrow_out, narrow_out),
        out_shape=(jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        interpret=interpret_mode(),
    )(x2, shift)
    m_s = s / m
    mean = m_s + shift
    var = jnp.maximum(s2 / m - jnp.square(m_s), 0.0)
    return mean, var


def _normalize(x2, scale, shift):
    from . import interpret_mode
    m, c = x2.shape
    br = _block_rows(c)
    wide, narrows = _row_specs(br, c, 2)
    return pl.pallas_call(
        _norm_kernel,
        grid=(pl.cdiv(m, br),),
        in_specs=[wide] + narrows,
        out_specs=wide,
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        interpret=interpret_mode(),
    )(x2, scale, shift)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _batch_norm2(x2, w, b, eps):
    """Returns (out, mean, var) — batch stats ride out of the same
    forward (the Layer's running-stat update consumes them), so no
    extra stats pass is ever taken."""
    out, mean, var, _ = _bn_fwd_res(x2, w, b, eps)
    return out, mean, var


def _bn_fwd_res(x2, w, b, eps):
    mean, var = _stats(x2)
    rstd = jax.lax.rsqrt(var + eps)
    wf = w.astype(jnp.float32).reshape(1, -1)
    scale = rstd * wf
    shift = b.astype(jnp.float32).reshape(1, -1) - mean * scale
    out = _normalize(x2, scale, shift)
    return out, mean, var, rstd


def _bn_fwd(x2, w, b, eps):
    out, mean, var, rstd = _bn_fwd_res(x2, w, b, eps)
    return (out, mean, var), (x2, w, mean, rstd)


def _bn_bwd(eps, res, gs):
    g, g_mean, g_var = gs
    x2, w, mean, rstd = res
    m, c = x2.shape
    br = _block_rows(c)
    wide, narrows = _row_specs(br, c, 2)
    narrow_out = pl.BlockSpec((1, c), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    from . import interpret_mode
    dg, db = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, m=m, br=br),
        grid=(pl.cdiv(m, br),),
        in_specs=[wide, wide] + narrows,
        out_specs=(narrow_out, narrow_out),
        out_shape=(jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        interpret=interpret_mode(),
    )(x2, g, mean, rstd)
    wr = (w.astype(jnp.float32).reshape(1, -1) * rstd)
    # cotangents of the direct mean/var outputs, pre-scaled and stacked
    # into one (2, C) operand: row 0 = gm/M, row 1 = 2·gv/M
    gmv = jnp.concatenate([
        jnp.asarray(g_mean, jnp.float32).reshape(1, c) / m,
        2.0 * jnp.asarray(g_var, jnp.float32).reshape(1, c) / m,
    ], axis=0)
    gmv_spec = pl.BlockSpec((2, c), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        _bwd_dx_kernel,
        grid=(pl.cdiv(m, br),),
        in_specs=[wide, wide] + [narrow_out] * 5 + [gmv_spec],
        out_specs=wide,
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        interpret=interpret_mode(),
    )(x2, g, mean, rstd, wr, dg / m, db / m, gmv)
    return dx, dg[0].astype(w.dtype), db[0].astype(w.dtype)


_batch_norm2.defvjp(_bn_fwd, _bn_bwd)


def bn_channels_last(x, w, b, epsilon):
    """Raw-array helper: fused BN over the LAST axis of any-rank x.
    Returns (out, mean(C,), var(C,)). The single shared body under both
    fused_batch_norm_train and nn_ops.batch_norm's Pallas branch."""
    cdim = x.shape[-1]
    lead = x.shape[:-1]
    out, mean, var = _batch_norm2(x.reshape(-1, cdim), w, b, epsilon)
    return (out.reshape(*lead, cdim), mean.reshape(cdim),
            var.reshape(cdim))


def fused_batch_norm_train(x, weight, bias, epsilon=1e-5):
    """Framework op: training-mode fused BN over the LAST axis (NHWC /
    NLC / (N, C)). Returns (out, batch_mean, batch_var) — the Layer
    folds the running-stat update on top. Differentiable w.r.t.
    x/weight/bias through the custom VJP (including exact handling of
    losses that consume the batch stats directly)."""
    from ...dispatch import apply

    def impl(x, w, b):
        return bn_channels_last(x, w, b, epsilon)

    return apply(impl, (x, weight, bias), n_out=3,
                 name="pallas_batch_norm")
