"""Flash attention Pallas kernels — fused mask + attention dropout + fused
backward (reference: the fused attention stack the reference approximates
with paddle/fluid/operators/fused/fused_elemwise_activation_op.cu +
softmax_with_cross_entropy_op.cu; flash-style tiling is the TPU-native
formulation).

Forward: grid (batch*heads, q-blocks); each program walks k/v-blocks with
the online-softmax recurrence (running max m, normalizer l, accumulator
acc) so the S×S score matrix never hits HBM. The additive attention mask
(key bias [B,1,1,Sk] or full [.,.,Sq,Sk]) is added to the scores inside
the kernel, and attention-probability dropout is drawn in-kernel from the
TPU PRNG, seeded per (bh, q-block, k-block) tile so the backward
regenerates the identical keep-mask without ever storing it.

Backward: two kernels. dQ: grid (bh, q-blocks) loops k-blocks; dK/dV:
grid (bh, k-blocks) loops q-blocks, accumulating dv = pd^T @ dO and
dk = ds^T @ Q. Both recompute p = exp(s - m) / l from the saved PER-ROW
(max m, normalizer l) — deliberately NOT the folded lse = m + log l: with
a finite large-negative additive mask (the -1e9 convention) s and m are
~1e9-scale where f32 ulp is 64, so s − m reproduces the forward's (and
sdpa's) rounding exactly while s − (m + log l) would silently lose the
entire log-normalizer. delta = rowsum(dO∘O) is one cheap XLA reduction
outside the kernels (the identity Σ_k p_k·dp_k = rowsum(dO∘O) holds under
dropout too). Row stats are stored (…, 1) between passes and broadcast to
(…, 128) lanes only transiently around each kernel call (Mosaic-trivial
layouts without holding 128× residual HBM — same lane-replication scheme
as the upstream pallas TPU attention kernel).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


def _dropout_keep(seed_ref, bh, qi, j, shape, threshold):
    """Regeneratable dropout keep-mask for one (BQ, BK) score tile, drawn
    from the TPU PRNG seeded per tile (so fwd and both bwd kernels
    regenerate the identical mask without storing it)."""
    # libtpu's tpu.prng_set_seed_32 takes at most TWO seed words, so fold
    # the (bh, qi, j) tile coordinates into one mixed word via a
    # murmur-style absorb (xor word, odd-constant multiply, logical
    # shift-xor) — avalanches all 32 bits, so no wrap-around collision
    # window at long sequences / large batch*heads (int32 ops wrap mod
    # 2^32 in XLA, which is exactly what the hash wants)
    mixed = seed_ref[1]
    for v in (bh, qi, j):
        mixed = (mixed ^ v) * jnp.int32(-1640531527)   # 0x9E3779B9
        mixed = mixed ^ ((mixed >> 15) & jnp.int32(0x1FFFF))
        mixed = mixed * jnp.int32(-1274126177)         # 0xB40E609F (odd)
        mixed = mixed ^ ((mixed >> 13) & jnp.int32(0x7FFFF))
    pltpu.prng_seed(seed_ref[0], mixed)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= jnp.uint32(threshold)


def _host_keep_mask(seed, bh, sq_pad, sk_pad, dropout_p):
    """Interpret-mode (CPU test) substitute: the TPU PRNG primitives have
    no CPU lowering, so precompute the whole keep-mask in XLA from the
    same seed (deterministic → fwd/bwd see identical masks) and thread it
    through as a kernel operand (0.0 = drop, 1.0 = keep)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed[0]), seed[1])
    u = jax.random.uniform(key, (bh, sq_pad, sk_pad))
    return (u >= dropout_p).astype(jnp.float32)


def _masked_scores(q, k, mask_ref, qi, j, *, block_q, block_k, sq, sk,
                   causal, mask_mode):
    """Scaled scores + additive bias with invalid positions at _NEG_INF.
    q is pre-scaled f32 (BQ, D); k is f32 (BK, D). mask_ref rows are
    already positioned by the BlockSpec ((1,BK) key bias broadcasts down,
    (BQ,BK) full bias adds elementwise)."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    if mask_mode in ("key", "full"):
        s = s + mask_ref[0, :, pl.ds(j * block_k, block_k)].astype(
            jnp.float32)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = (q_pos < sq) & (k_pos < sk)
    if causal:
        valid = valid & (q_pos >= k_pos)
    return jnp.where(valid, s, _NEG_INF), valid


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, keep_ref, o_ref,
                m_ref, l_ref, *, block_q, block_k, sq, sk, causal, scale,
                mask_mode, dropout_p, threshold, drop_mode):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, SKp, D); mask_ref: (1,{1,BQ},SKp)
    q = q_ref[0].astype(jnp.float32) * scale
    bh = pl.program_id(0)
    qi = pl.program_id(1)

    m0 = jnp.full((q.shape[0], 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc0 = jnp.zeros((q.shape[0], q_ref.shape[2]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        # zero padded v rows: p is 0 there, but 0 * NaN-padding would
        # still poison the accumulator
        row_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        v = jnp.where(row_pos < sk, v, 0.0)
        s, _ = _masked_scores(q, k, mask_ref, qi, j, block_q=block_q,
                              block_k=block_k, sq=sq, sk=sk, causal=causal,
                              mask_mode=mask_mode)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # rows with every key masked: keep the exp argument finite
        m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(s <= _NEG_INF, 0.0, p)
        corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            if drop_mode == "prng":
                keep = _dropout_keep(seed_ref, bh, qi, j, p.shape,
                                     threshold)
            else:
                keep = keep_ref[0, :, pl.ds(j * block_k, block_k)] > 0.5
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        acc_new = acc * corr + jnp.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    nk = pl.cdiv(sk, block_k)
    nk_needed = nk if not causal else jnp.minimum(
        nk, pl.cdiv((qi + 1) * block_q, block_k))
    m, l, acc = jax.lax.fori_loop(0, nk_needed, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
    m_fin = jnp.where(m <= _NEG_INF, 0.0, m)
    m_ref[0] = jax.lax.broadcast_in_dim(m_fin, m_ref.shape[1:], (0, 1))
    l_ref[0] = jax.lax.broadcast_in_dim(l, l_ref.shape[1:], (0, 1))


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, keep_ref,
                   m_ref, linv_ref, delta_ref, do_ref, dq_ref, *, block_q,
                   block_k, sq, sk, causal, scale, mask_mode, dropout_p,
                   threshold, drop_mode):
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    mrow = m_ref[0][:, :1]       # (BQ, 1)
    linv = linv_ref[0][:, :1]    # (BQ, 1)
    delta = delta_ref[0][:, :1]  # (BQ, 1)
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    dq0 = jnp.zeros((q.shape[0], q_ref.shape[2]), jnp.float32)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s, valid = _masked_scores(q, k, mask_ref, qi, j, block_q=block_q,
                                  block_k=block_k, sq=sq, sk=sk,
                                  causal=causal, mask_mode=mask_mode)
        # p = exp(s − m)/l: same rounding as the forward recurrence even
        # for ~1e9-scale masked scores (see module docstring)
        p = jnp.where(valid, jnp.exp(s - mrow) * linv, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            if drop_mode == "prng":
                keep = _dropout_keep(seed_ref, bh, qi, j, p.shape,
                                     threshold)
            else:
                keep = keep_ref[0, :, pl.ds(j * block_k, block_k)] > 0.5
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    nk = pl.cdiv(sk, block_k)
    nk_needed = nk if not causal else jnp.minimum(
        nk, pl.cdiv((qi + 1) * block_q, block_k))
    dq = jax.lax.fori_loop(0, nk_needed, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, keep_ref,
                    m_ref, linv_ref, delta_ref, do_ref, dk_ref, dv_ref, *,
                    block_q, block_k, sq, sk, causal, scale, mask_mode,
                    dropout_p, threshold, drop_mode):
    # this program owns ONE k-block (grid (bh, k-blocks)) and loops
    # q-blocks. q_ref/do_ref: (1, SQp, D); k_ref/v_ref: (1, BK, D);
    # mask_ref: (1, {1, SQp}, BK); m/linv/delta: (1, SQp, LANES)
    bh = pl.program_id(0)
    j = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(
            jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        mrow = m_ref[0, pl.ds(qi * block_q, block_q), :][:, :1]
        linv = linv_ref[0, pl.ds(qi * block_q, block_q), :][:, :1]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if mask_mode == "key":
            s = s + mask_ref[0, :, :].astype(jnp.float32)  # (1, BK)
        elif mask_mode == "full":
            s = s + mask_ref[0, pl.ds(qi * block_q, block_q), :].astype(
                jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = (q_pos < sq) & (k_pos < sk)
        if causal:
            valid = valid & (q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(jnp.where(valid, s, _NEG_INF) - mrow)
                      * linv, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        pd = p
        if dropout_p > 0.0:
            if drop_mode == "prng":
                keep = _dropout_keep(seed_ref, bh, qi, j, p.shape,
                                     threshold)
            else:
                keep = keep_ref[0, pl.ds(qi * block_q, block_q), :] > 0.5
            pd = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        dv = dv + jnp.dot(pd.T, do, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # q above is pre-scaled, so ds^T @ (q·scale) is already dk
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    nq = pl.cdiv(sq, block_q)
    q_start = 0 if not causal else (j * block_k) // block_q
    dk, dv = jax.lax.fori_loop(q_start, nq, body,
                               (jnp.zeros_like(k), jnp.zeros_like(v)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _mask_mode(mask_shape, b, h, sq, sk):
    """Static tiling decision from the mask's 4D-normalized shape:
    'key' (broadcasts over queries), 'full', or 'fallback'."""
    if mask_shape is None:
        return None
    shape = (1,) * (4 - len(mask_shape)) + tuple(mask_shape)
    if len(shape) != 4:
        return "fallback"
    mb, mh, msq, msk = shape
    if msk != sk or mb not in (1, b) or mh not in (1, h) or \
            msq not in (1, sq):
        return "fallback"
    return "key" if msq == 1 else "full"


def _canon_mask(m):
    """Numeric canonicalization: bool→additive, f32, 4D."""
    if m.dtype == jnp.bool_:
        m = jnp.where(m, 0.0, _NEG_INF).astype(jnp.float32)
    else:
        m = m.astype(jnp.float32)
    while m.ndim < 4:
        m = m[None]
    return m


def _mask_operand(mask, mode, h, sq_pad, sk_pad):
    """mask (mb,mh,msq,msk) → ((G, {1|SQp}, SKp) array, bh→G index fn)."""
    mb, mh, msq, msk = mask.shape
    pad_q = (sq_pad - msq) if mode == "full" else 0
    m = jnp.pad(mask, [(0, 0), (0, 0), (0, pad_q), (0, sk_pad - msk)])
    m3 = m.reshape(mb * mh, m.shape[2], sk_pad)

    def bh_to_g(i):
        if mb == 1 and mh == 1:
            return 0
        if mb == 1:
            return i % h
        if mh == 1:
            return i // h
        return i

    return m3, bh_to_g


def _pad_axis(x, axis, new):
    if x.shape[axis] == new:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, new - x.shape[axis])
    return jnp.pad(x, pads)


def _lanes(stat, sq_pad):
    """(BH, SQ, 1) row stat → transient lane-replicated (BH, SQp, LANES)."""
    stat = _pad_axis(stat, 1, sq_pad)
    return jnp.broadcast_to(stat, stat.shape[:2] + (_LANES,))


def _flash_fwd_res(q, k, v, mask, mask_mode, seed, causal, scale, block_q,
                   block_k, dropout_p):
    from . import interpret_mode
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, max(sq, 8))
    bk = min(block_k, sk)
    # pad K/V up to a block multiple: a manual pl.ds read past the end
    # CLAMPS its start (dynamic-slice semantics) and would silently re-read
    # earlier rows; the kernels mask positions >= the true sk
    sk_pad = -(-sk // bk) * bk
    sq_pad = -(-sq // bq) * bq
    q3 = q.reshape(b * h, sq, d)
    k3 = _pad_axis(k.reshape(b * h, sk, d), 1, sk_pad)
    v3 = _pad_axis(v.reshape(b * h, sk, d), 1, sk_pad)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    threshold = min(int(dropout_p * 4294967296.0), 4294967295)

    if mask_mode in ("key", "full"):
        m3, bh_to_g = _mask_operand(mask, mask_mode, h, sq_pad, sk_pad)
        if mask_mode == "key":
            mspec = pl.BlockSpec((1, 1, sk_pad),
                                 lambda i, j: (bh_to_g(i), 0, 0),
                                 memory_space=pltpu.VMEM)
        else:  # block the query dim: only (BQ, SKp) of bias in VMEM
            mspec = pl.BlockSpec((1, bq, sk_pad),
                                 lambda i, j: (bh_to_g(i), j, 0),
                                 memory_space=pltpu.VMEM)
    else:
        m3 = jnp.zeros((1, 1, sk_pad), jnp.float32)
        mspec = pl.BlockSpec((1, 1, sk_pad), lambda i, j: (0, 0, 0),
                             memory_space=pltpu.VMEM)
    seed2 = jnp.asarray(seed, jnp.int32).reshape(2)
    interp = interpret_mode()
    drop_mode = "mask" if (interp and dropout_p > 0.0) else "prng"
    if drop_mode == "mask":
        keep3 = _host_keep_mask(seed2, b * h, sq_pad, sk_pad, dropout_p)
        kspec = pl.BlockSpec((1, bq, sk_pad), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    else:
        keep3 = jnp.zeros((1, 1, 1), jnp.float32)
        kspec = pl.BlockSpec((1, 1, 1), lambda i, j: (0, 0, 0),
                             memory_space=pltpu.VMEM)

    out, mrow, lrow = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=bq, block_k=bk, sq=sq, sk=sk,
            causal=causal, scale=s, mask_mode=mask_mode,
            dropout_p=dropout_p, threshold=threshold, drop_mode=drop_mode),
        grid=(b * h, pl.cdiv(sq, bq)),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            mspec,
            kspec,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, _LANES), jnp.float32),
        ],
        interpret=interp,
    )(seed2, q3, k3, v3, m3, keep3)
    # keep only one lane as residuals (128× smaller across the fwd→bwd gap)
    return out.reshape(b, h, sq, d), mrow[..., :1], lrow[..., :1]


def _flash_bwd(q, k, v, mask, mask_mode, seed, out, mrow, lrow, g, causal,
               scale, block_q, block_k, dropout_p):
    from . import interpret_mode
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, max(sq, 8))
    bk = min(block_k, sk)
    sk_pad = -(-sk // bk) * bk
    sq_pad = -(-sq // bq) * bq
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    threshold = min(int(dropout_p * 4294967296.0), 4294967295)

    q3 = q.reshape(b * h, sq, d)
    k3 = _pad_axis(k.reshape(b * h, sk, d), 1, sk_pad)
    v3 = _pad_axis(v.reshape(b * h, sk, d), 1, sk_pad)
    do3 = g.reshape(b * h, sq, d)
    # delta_i = Σ_d dO_id·O_id (= Σ_k p_ik·dp_ik — valid under dropout too)
    delta = jnp.sum(do3.astype(jnp.float32) *
                    out.reshape(b * h, sq, d).astype(jnp.float32), axis=-1,
                    keepdims=True)
    linv = 1.0 / jnp.maximum(lrow, 1e-20)
    mb_l = _lanes(mrow, sq_pad)
    linv_l = _lanes(linv, sq_pad)
    delta_l = _lanes(delta, sq_pad)

    if mask_mode in ("key", "full"):
        m3, bh_to_g = _mask_operand(mask, mask_mode, h, sq_pad, sk_pad)
    else:
        m3 = jnp.zeros((1, 1, sk_pad), jnp.float32)
        bh_to_g = lambda i: 0
    seed2 = jnp.asarray(seed, jnp.int32).reshape(2)
    msq_blk = 1 if mask_mode != "full" else sq_pad
    interp = interpret_mode()
    drop_mode = "mask" if (interp and dropout_p > 0.0) else "prng"
    if drop_mode == "mask":
        keep3 = _host_keep_mask(seed2, b * h, sq_pad, sk_pad, dropout_p)
        kspec_q = pl.BlockSpec((1, bq, sk_pad), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM)
        kspec_kv = pl.BlockSpec((1, sq_pad, bk), lambda i, j: (i, 0, j),
                                memory_space=pltpu.VMEM)
    else:
        keep3 = jnp.zeros((1, 1, 1), jnp.float32)
        kspec_q = pl.BlockSpec((1, 1, 1), lambda i, j: (0, 0, 0),
                               memory_space=pltpu.VMEM)
        kspec_kv = kspec_q
    if mask_mode == "full":
        mspec_q = pl.BlockSpec((1, bq, sk_pad),
                               lambda i, j: (bh_to_g(i), j, 0),
                               memory_space=pltpu.VMEM)
    else:
        mspec_q = pl.BlockSpec((1, 1, sk_pad),
                               lambda i, j: (bh_to_g(i), 0, 0),
                               memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=bq, block_k=bk, sq=sq, sk=sk,
            causal=causal, scale=s, mask_mode=mask_mode,
            dropout_p=dropout_p, threshold=threshold, drop_mode=drop_mode),
        grid=(b * h, pl.cdiv(sq, bq)),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            mspec_q,
            kspec_q,
            pl.BlockSpec((1, bq, _LANES), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interp,
    )(seed2, q3, k3, v3, m3, keep3, mb_l, linv_l, delta_l, do3)

    # dK/dV pass needs whole-Q operands padded to the block multiple
    q3p = _pad_axis(q3, 1, sq_pad)
    do3p = _pad_axis(do3, 1, sq_pad)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, block_k=bk, sq=sq, sk=sk,
            causal=causal, scale=s, mask_mode=mask_mode,
            dropout_p=dropout_p, threshold=threshold, drop_mode=drop_mode),
        grid=(b * h, pl.cdiv(sk, bk)),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, sq_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, msq_blk, bk), lambda i, j: (bh_to_g(i), 0, j),
                         memory_space=pltpu.VMEM),
            kspec_kv,
            pl.BlockSpec((1, sq_pad, _LANES), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sq_pad, _LANES), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sq_pad, _LANES), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sq_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk_pad, d), v.dtype),
        ],
        interpret=interp,
    )(seed2, q3p, k3, v3, m3, keep3, mb_l, linv_l, delta_l, do3p)
    dk = dk[:, :sk].reshape(b, h, sk, d)
    dv = dv[:, :sk].reshape(b, h, sk, d)
    return dq.reshape(b, h, sq, d), dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 6, 7, 8, 9, 10))
def _flash(q, k, v, mask, mask_mode, seed, causal, scale, block_q, block_k,
           dropout_p):
    out, _, _ = _flash_fwd_res(q, k, v, mask, mask_mode, seed, causal,
                               scale, block_q, block_k, dropout_p)
    return out


def _fwd(q, k, v, mask, mask_mode, seed, causal, scale, block_q, block_k,
         dropout_p):
    out, mrow, lrow = _flash_fwd_res(q, k, v, mask, mask_mode, seed, causal,
                                     scale, block_q, block_k, dropout_p)
    return out, (q, k, v, mask, seed, out, mrow, lrow)


def _bwd(mask_mode, causal, scale, block_q, block_k, dropout_p, res, g):
    q, k, v, mask, seed, out, mrow, lrow = res
    dq, dk, dv = _flash_bwd(q, k, v, mask, mask_mode, seed, out, mrow,
                            lrow, g, causal, scale, block_q, block_k,
                            dropout_p)
    # mask is an input-derived bias — not differentiated (reference parity)
    dmask = None if mask is None else jnp.zeros_like(mask)
    dseed = np.zeros(np.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dmask, dseed


_flash.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, attn_mask=None, causal=False, scale=None,
                    block_q=512, block_k=1024, dropout_p=0.0,
                    training=False, force=False, name=None):
    """Framework op: flash attention over (B, H, S, D). The additive (or
    bool) attn_mask and attention-probability dropout are fused into the
    kernels; mask shapes the kernel can't tile (non-broadcastable to
    (B,H,Sq,Sk)) fall back to plain sdpa with identical semantics.
    Off-TPU the op also falls back to sdpa (the interpret-mode kernel is
    emulator-speed) unless force=True (kernel correctness tests)."""
    from ...dispatch import apply
    from ... import random as prandom
    from . import enabled

    b, h, sq, d = q.shape
    sk = k.shape[2]
    p_drop = float(dropout_p) if training else 0.0
    has_mask = attn_mask is not None
    mode = _mask_mode(attn_mask.shape if has_mask else None, b, h, sq, sk)
    if mode == "fallback" or (not force and
                              not enabled("flash_attention",
                                          seq_len=max(sq, sk))):
        from ..nn_ops import scaled_dot_product_attention as sdpa
        return sdpa(q, k, v, attn_mask=attn_mask, is_causal=causal,
                    scale=scale, dropout_p=p_drop, training=training)

    def impl(q, k, v, *rest):
        m = _canon_mask(rest[0]) if has_mask else None
        if p_drop > 0.0:
            raw = jnp.ravel(rest[-1])[:2]
            seed = jax.lax.bitcast_convert_type(raw, jnp.int32)
        else:
            seed = jnp.zeros((2,), jnp.int32)
        return _flash(q, k, v, m, mode, seed, causal, scale, block_q,
                      block_k, p_drop)

    args = (q, k, v)
    if has_mask:
        args = args + (attn_mask,)
    if p_drop > 0.0:
        args = args + (prandom.next_key_graph(),)
    return apply(impl, args, name="pallas_flash_attention")
