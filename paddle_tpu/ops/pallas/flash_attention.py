"""Flash attention Pallas kernel (reference: the fused attention the
reference approximates with fused_elemwise + softmax kernels; modern
flash-style tiling is the TPU-native formulation).

Forward: grid (batch*heads, q-blocks); for each q-block a fori_loop walks
k/v-blocks with the online-softmax recurrence (running max m, normalizer l,
accumulator acc in VMEM scratch) — attention never materializes the S×S
matrix in HBM. Backward currently recomputes with the standard einsum
formulation under XLA (documented trade-off; a full flash backward kernel
is a later-round optimization).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, sk, causal, scale,
                block_q):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, SK, D)
    q = q_ref[0].astype(jnp.float32) * scale
    qi = pl.program_id(1)

    m0 = jnp.full((q.shape[0], 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc0 = jnp.zeros((q.shape[0], q_ref.shape[2]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        # zero padded v rows: p is 0 there, but 0 * NaN-padding would
        # still poison the accumulator
        row_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        v = jnp.where(row_pos < sk, v, 0.0)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # mask keys past the true sequence end (tail block when
        # sk % block_k != 0 reads padding)
        s = jnp.where(k_pos < sk, s, -jnp.inf)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jnp.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    nk = pl.cdiv(sk, block_k)
    nk_needed = nk if not causal else jnp.minimum(
        nk, pl.cdiv((qi + 1) * block_q, block_k))
    m, l, acc = jax.lax.fori_loop(0, nk_needed, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    from . import interpret_mode
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    # pad K/V up to a block multiple: a manual pl.ds read past the end
    # CLAMPS its start (dynamic-slice semantics) and would silently re-read
    # earlier rows; the kernel masks positions >= true sk
    sk_pad = -(-sk // bk) * bk
    if sk_pad != sk:
        padw = [(0, 0), (0, sk_pad - sk), (0, 0)]
        k3 = jnp.pad(k3, padw)
        v3 = jnp.pad(v3, padw)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=bk, sk=sk, causal=causal,
                          scale=s, block_q=bq),
        grid=(b * h, pl.cdiv(sq, bq)),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret_mode(),
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    # recompute-based backward (XLA): standard attention gradients
    q, k, v = res
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / np.sqrt(d)

    def ref_attn(q, k, v):
        logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        if causal:
            # top-left aligned (query i sees keys j <= i), matching the
            # forward kernel's absolute-position mask for sq != sk
            sq, sk = logits.shape[-2:]
            mask = jnp.tril(jnp.ones((sq, sk), bool))
            logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    _, vjp = jax.vjp(ref_attn, q, k, v)
    return vjp(g)


_flash.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, attn_mask=None, causal=False, scale=None,
                    block_q=256, block_k=256, dropout_p=0.0, training=False,
                    name=None):
    """Framework op: flash attention over (B, H, S, D). attn_mask and
    attention dropout are not fused — both fall back to plain sdpa so
    behavior matches the unfused path exactly."""
    from ...dispatch import apply
    if attn_mask is not None or (dropout_p > 0.0 and training):
        from ..nn_ops import scaled_dot_product_attention
        return scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=causal, scale=scale,
            dropout_p=dropout_p, training=training)

    def impl(q, k, v):
        return _flash(q, k, v, causal, scale, block_q, block_k)

    return apply(impl, (q, k, v), name="pallas_flash_attention")
