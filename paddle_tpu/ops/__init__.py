"""paddle_tpu.ops — the functional op library (≈250 ops).

TPU-native rebuild of the reference's operator zoo
(reference: paddle/fluid/operators/* with python surface in
python/paddle/fluid/layers/). Every op is one pure-jax impl dispatched
through paddle_tpu.dispatch.apply, so a single definition serves dygraph
(tape autograd), jit-traced to_static, and static Program recording.

This module also attaches the numeric magic methods to Tensor (done here
rather than in tensor.py to break the import cycle — same role as the
reference's monkey-patching in python/paddle/fluid/dygraph/math_op_patch.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from .math import *  # noqa: F401,F403
from .manip import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .nn_ops import *  # noqa: F401,F403
from .control_flow import cond, while_loop, case, switch_case  # noqa: F401
from .imperative_flow import (IfElse, Switch, DynamicRNN,  # noqa: F401
                              TensorArray, create_array, array_write,
                              array_read, array_length)
from . import loss  # noqa: F401
from . import detection  # noqa: F401
from . import math as math_ops
from . import manip as manip_ops
from . import nn_ops
from . import creation as creation_ops
from ..dispatch import apply


# ---------------------------------------------------------------------------
# Tensor magic-method patching (reference: math_op_patch.py monkeypatch_math)

def _getitem(self, idx):
    def _fix(i):
        if isinstance(i, Tensor):
            return i.data
        return i
    if isinstance(idx, tuple):
        jidx = tuple(_fix(i) for i in idx)
    else:
        jidx = _fix(idx)
    return apply(lambda x, jidx: x[jidx], (self,), dict(jidx=jidx),
                 name="getitem")


def _setitem(self, idx, value):
    if isinstance(value, Tensor):
        value = value.data
    if isinstance(idx, Tensor):
        idx = idx.data
    self.data = self.data.at[idx].set(value)
    return self


def _patch():
    T = Tensor
    T.__add__ = lambda s, o: math_ops.add(s, o)
    T.__radd__ = lambda s, o: math_ops.add(o, s)
    T.__sub__ = lambda s, o: math_ops.subtract(s, o)
    T.__rsub__ = lambda s, o: math_ops.subtract(o, s)
    T.__mul__ = lambda s, o: math_ops.multiply(s, o)
    T.__rmul__ = lambda s, o: math_ops.multiply(o, s)
    T.__truediv__ = lambda s, o: math_ops.divide(s, o)
    T.__rtruediv__ = lambda s, o: math_ops.divide(o, s)
    T.__floordiv__ = lambda s, o: math_ops.floor_divide(s, o)
    T.__mod__ = lambda s, o: math_ops.mod(s, o)
    T.__pow__ = lambda s, o: math_ops.pow(s, o)
    T.__rpow__ = lambda s, o: math_ops.pow(o, s)
    T.__neg__ = lambda s: math_ops.negative(s)
    T.__abs__ = lambda s: math_ops.abs(s)
    T.__matmul__ = lambda s, o: math_ops.matmul(s, o)
    T.__rmatmul__ = lambda s, o: math_ops.matmul(o, s)
    T.__eq__ = lambda s, o: math_ops.equal(s, o)
    T.__ne__ = lambda s, o: math_ops.not_equal(s, o)
    T.__lt__ = lambda s, o: math_ops.less_than(s, o)
    T.__le__ = lambda s, o: math_ops.less_equal(s, o)
    T.__gt__ = lambda s, o: math_ops.greater_than(s, o)
    T.__ge__ = lambda s, o: math_ops.greater_equal(s, o)
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem
    # tensor methods (paddle Tensor method surface)
    T.matmul = lambda s, o, transpose_x=False, transpose_y=False: \
        math_ops.matmul(s, o, transpose_x, transpose_y)
    T.mm = T.matmul
    T.reshape = lambda s, shape: manip_ops.reshape(s, shape)
    T.transpose = lambda s, perm: manip_ops.transpose(s, perm)
    T.flatten = lambda s, start_axis=0, stop_axis=-1: manip_ops.flatten(
        s, start_axis, stop_axis)
    T.squeeze = lambda s, axis=None: manip_ops.squeeze(s, axis)
    T.unsqueeze = lambda s, axis: manip_ops.unsqueeze(s, axis)
    T.sum = lambda s, axis=None, keepdim=False: math_ops.sum(s, axis,
                                                             keepdim)
    T.mean = lambda s, axis=None, keepdim=False: math_ops.mean(s, axis,
                                                               keepdim)
    T.max = lambda s, axis=None, keepdim=False: math_ops.max(s, axis,
                                                             keepdim)
    T.min = lambda s, axis=None, keepdim=False: math_ops.min(s, axis,
                                                             keepdim)
    T.prod = lambda s, axis=None, keepdim=False: math_ops.prod(s, axis,
                                                               keepdim)
    T.argmax = lambda s, axis=None, keepdim=False: math_ops.argmax(
        s, axis, keepdim)
    T.argmin = lambda s, axis=None, keepdim=False: math_ops.argmin(
        s, axis, keepdim)
    T.exp = lambda s: math_ops.exp(s)
    T.log = lambda s: math_ops.log(s)
    T.sqrt = lambda s: math_ops.sqrt(s)
    T.square = lambda s: math_ops.square(s)
    T.abs = lambda s: math_ops.abs(s)
    T.tanh = lambda s: math_ops.tanh(s)
    T.sigmoid = lambda s: nn_ops.sigmoid(s)
    T.clip = lambda s, min=None, max=None: math_ops.clip(s, min, max)
    T.pow = lambda s, o: math_ops.pow(s, o)
    T.norm = lambda s, p=2, axis=None, keepdim=False: math_ops.norm(
        s, p, axis, keepdim)
    T.gather = lambda s, index, axis=0: manip_ops.gather(s, index, axis)
    T.concat = staticmethod(manip_ops.concat)
    T.split = lambda s, n, axis=0: manip_ops.split(s, n, axis)
    T.tile = lambda s, reps: manip_ops.tile(s, reps)
    T.expand = lambda s, shape: manip_ops.expand(s, shape)
    T.flip = lambda s, axis: manip_ops.flip(s, axis)
    T.cumsum = lambda s, axis=None: math_ops.cumsum(s, axis)
    T.topk = lambda s, k, axis=-1: math_ops.topk(s, k, axis)
    T.sort = lambda s, axis=-1, descending=False: math_ops.sort(
        s, axis, descending)
    T.argsort = lambda s, axis=-1, descending=False: math_ops.argsort(
        s, axis, descending)
    T.add = lambda s, o: math_ops.add(s, o)
    T.subtract = lambda s, o: math_ops.subtract(s, o)
    T.multiply = lambda s, o: math_ops.multiply(s, o)
    T.divide = lambda s, o: math_ops.divide(s, o)
    T.scale = lambda s, scale=1.0, bias=0.0: math_ops.scale(s, scale, bias)
    T.unbind = lambda s, axis=0: manip_ops.unstack(s, axis)


_patch()
del _patch
from . import sequence  # noqa: F401
from .sequence import (sequence_pool, sequence_softmax,  # noqa: F401
                       sequence_reverse, sequence_expand, sequence_pad,
                       sequence_unpad, sequence_concat, sequence_conv,
                       sequence_slice, sequence_expand_as,
                       sequence_reshape, sequence_scatter,
                       sequence_enumerate, sequence_first_step,
                       sequence_last_step)
from . import crf  # noqa: F401
from .crf import linear_chain_crf, crf_decoding  # noqa: F401
from . import ctc  # noqa: F401
from .ctc import ctc_loss, warpctc, ctc_greedy_decoder  # noqa: F401
