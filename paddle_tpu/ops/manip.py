"""paddle_tpu.ops.manip — shape/layout/index manipulation ops.

TPU-native rebuild of the reference's tensor-manipulation operators
(reference: paddle/fluid/operators/{reshape_op, transpose_op, concat_op,
split_op, slice_op, gather_op, scatter_op, stack_op, squeeze_op, expand_op,
pad_op, one_hot_op}.cc; python surface in fluid/layers/nn.py + tensor.py).
All static-shape friendly: XLA requires static shapes under jit, so dynamic
outputs (e.g. masked select) are either avoided or documented as eager-only.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor, convert_dtype
from ..dispatch import apply

_slice = __builtins__["slice"] if isinstance(__builtins__, dict) else __builtins__.slice


def reshape(x, shape, name=None):
    def impl(x, shape):
        return jnp.reshape(x, shape)
    return apply(impl, (x,), dict(shape=tuple(shape)), name="reshape")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(x, start_axis, stop_axis):
        nd = x.ndim
        sa = start_axis % nd
        so = stop_axis % nd
        new_shape = x.shape[:sa] + (-1,) + x.shape[so + 1:]
        return jnp.reshape(x, new_shape)
    return apply(impl, (x,), dict(start_axis=start_axis, stop_axis=stop_axis),
                 name="flatten")


def transpose(x, perm, name=None):
    return apply(lambda x, perm: jnp.transpose(x, perm), (x,),
                 dict(perm=tuple(perm)), name="transpose")


def concat(xs, axis=0, name=None):
    def impl(*arrays, axis):
        return jnp.concatenate(arrays, axis=axis)
    return apply(impl, tuple(xs), dict(axis=axis), name="concat")


def split(x, num_or_sections, axis=0, name=None):
    """reference: split_op.cc — returns a list of tensors."""
    def impl(x, num_or_sections, axis):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(x, num_or_sections, axis=axis))
        sizes = list(num_or_sections)
        total = x.shape[axis]
        if -1 in sizes:
            known = sum(s for s in sizes if s != -1)
            sizes[sizes.index(-1)] = total - known
        offsets = []
        acc = 0
        for s in sizes[:-1]:
            acc += s
            offsets.append(acc)
        return tuple(jnp.split(x, offsets, axis=axis))
    n = num_or_sections if isinstance(num_or_sections, int) else len(
        num_or_sections)
    sections = (tuple(num_or_sections)
                if not isinstance(num_or_sections, int) else num_or_sections)
    out = apply(impl, (x,), dict(num_or_sections=sections, axis=axis),
                n_out=n, name="split")
    return list(out)


def stack(xs, axis=0, name=None):
    def impl(*arrays, axis):
        return jnp.stack(arrays, axis=axis)
    return apply(impl, tuple(xs), dict(axis=axis), name="stack")


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else as_tensor(x).shape[axis]
    def impl(x, axis, n):
        return tuple(jnp.moveaxis(x, axis, 0)[i] for i in range(n))
    out = apply(impl, (x,), dict(axis=axis, n=n), n_out=n, name="unstack")
    return list(out)


def squeeze(x, axis=None, name=None):
    def impl(x, axis):
        if axis is None:
            return jnp.squeeze(x)
        axes = axis if isinstance(axis, tuple) else (axis,)
        axes = tuple(a for a in axes if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axes) if axes else x
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(impl, (x,), dict(axis=ax), name="squeeze")


def unsqueeze(x, axis, name=None):
    def impl(x, axis):
        axes = axis if isinstance(axis, tuple) else (axis,)
        for a in sorted(axes):
            x = jnp.expand_dims(x, a)
        return x
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(impl, (x,), dict(axis=ax), name="unsqueeze")


def expand(x, shape, name=None):
    """reference: expand_op.cc (expand_v2 semantics: -1 keeps dim)."""
    def impl(x, shape):
        shape = list(shape)
        offset = len(shape) - x.ndim
        for i in range(len(shape)):
            if shape[i] == -1:
                shape[i] = x.shape[i - offset]
        return jnp.broadcast_to(x, tuple(shape))
    return apply(impl, (x,), dict(shape=tuple(shape)), name="expand")


broadcast_to = expand


def expand_as(x, y, name=None):
    return apply(lambda x, y: jnp.broadcast_to(x, y.shape), (x, y),
                 name="expand_as")


def tile(x, repeat_times, name=None):
    return apply(lambda x, reps: jnp.tile(x, reps), (x,),
                 dict(reps=tuple(repeat_times)), name="tile")


def slice(x, axes, starts, ends, name=None):
    """reference: slice_op.cc"""
    def impl(x, axes, starts, ends):
        idx = [_slice(None)] * x.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = _slice(st, en)
        return x[tuple(idx)]
    return apply(impl, (x,), dict(axes=tuple(axes), starts=tuple(starts),
                                  ends=tuple(ends)), name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def impl(x, axes, starts, ends, strides):
        idx = [_slice(None)] * x.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            idx[ax] = _slice(st, en, sr)
        return x[tuple(idx)]
    return apply(impl, (x,), dict(axes=tuple(axes), starts=tuple(starts),
                                  ends=tuple(ends), strides=tuple(strides)),
                 name="strided_slice")


def gather(x, index, axis=0, name=None):
    """reference: gather_op.cc — gather rows along axis."""
    def impl(x, index, axis):
        return jnp.take(x, index, axis=axis)
    return apply(impl, (x, index), dict(axis=axis), name="gather")


def gather_nd(x, index, name=None):
    """reference: gather_nd_op.cc"""
    def impl(x, index):
        return x[tuple(jnp.moveaxis(index, -1, 0))]
    return apply(impl, (x, index), name="gather_nd")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis, name=name)


def scatter(x, index, updates, overwrite=True, name=None):
    """reference: scatter_op.cc — writes updates rows into x at index."""
    def impl(x, index, updates, overwrite):
        if overwrite:
            return x.at[index].set(updates)
        # accumulate semantics: zero the rows then add (matches reference)
        zeroed = x.at[index].set(jnp.zeros_like(updates))
        return zeroed.at[index].add(updates)
    return apply(impl, (x, index, updates), dict(overwrite=overwrite),
                 name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def impl(x, index, updates):
        return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)
    return apply(impl, (x, index, updates), name="scatter_nd_add")


def put_along_axis(x, index, values, axis, name=None):
    def impl(x, index, values, axis):
        return jnp.put_along_axis(x, index, values, axis=axis,
                                  inplace=False)
    return apply(impl, (x, index, values), dict(axis=axis),
                 name="put_along_axis")


def take_along_axis(x, index, axis, name=None):
    def impl(x, index, axis):
        return jnp.take_along_axis(x, index, axis=axis)
    return apply(impl, (x, index), dict(axis=axis), name="take_along_axis")


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda x, axis: jnp.flip(x, axis=axis), (x,),
                 dict(axis=ax), name="flip")


reverse = flip


def roll(x, shifts, axis=None, name=None):
    return apply(lambda x, shifts, axis: jnp.roll(x, shifts, axis=axis),
                 (x,), dict(shifts=shifts, axis=axis), name="roll")


def pad(x, pad, mode="constant", value=0.0, name=None):
    """paddle pad: flat list [lo0, hi0, lo1, hi1, ...] over ALL dims (old
    fluid.layers.pad) — we accept that plus paddle2-style per-last-dims."""
    def impl(x, pad, mode, value):
        if len(pad) == 2 * x.ndim:
            # fluid.layers.pad flat form: ascending dim order
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
        else:
            # paddle2/torch form: last dim first ([left,right,top,bottom])
            n = len(pad) // 2
            pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(n)]
            widths = [(0, 0)] * (x.ndim - n) + pairs[::-1]
        if mode == "constant":
            return jnp.pad(x, widths, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(x, widths, mode=jmode)
    return apply(impl, (x,), dict(pad=tuple(pad), mode=mode, value=value),
                 name="pad")


def one_hot(x, num_classes, name=None):
    """reference: one_hot_op.cc"""
    def impl(x, num_classes):
        return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)
    out = apply(impl, (x,), dict(num_classes=num_classes), nondiff=True,
                name="one_hot")
    return out


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           name=None):
    """Eager-only (dynamic output shape — not jittable on TPU)."""
    x = as_tensor(x)
    import numpy as np
    arr = np.asarray(jax.device_get(x.data))
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def masked_select(x, mask, name=None):
    """Eager-only (dynamic output shape)."""
    x, mask = as_tensor(x), as_tensor(mask)
    import numpy as np
    arr = np.asarray(jax.device_get(x.data))
    m = np.asarray(jax.device_get(mask.data))
    return Tensor(arr[m])


def diag(x, offset=0, name=None):
    return apply(lambda x, offset: jnp.diag(x, k=offset), (x,),
                 dict(offset=offset), name="diag")


def tril(x, diagonal=0, name=None):
    return apply(lambda x, k: jnp.tril(x, k=k), (x,), dict(k=diagonal),
                 name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda x, k: jnp.triu(x, k=k), (x,), dict(k=diagonal),
                 name="triu")


def meshgrid(*xs, name=None):
    n = len(xs)
    def impl(*arrays):
        return tuple(jnp.meshgrid(*arrays, indexing="ij"))
    return list(apply(impl, tuple(xs), n_out=n, name="meshgrid"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis, name=name)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: shard_index_op.cc (used by the PS/CTR path): map global ids
    to shard-local ids, others to ignore_value."""
    def impl(x, index_num, nshards, shard_id, ignore_value):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        hi = (shard_id + 1) * shard_size
        in_shard = (x >= lo) & (x < hi)
        return jnp.where(in_shard, x - lo, ignore_value)
    return apply(impl, (input,), dict(index_num=index_num, nshards=nshards,
                                      shard_id=shard_id,
                                      ignore_value=ignore_value),
                 nondiff=True, name="shard_index")


def unbind(input, axis=0):
    """reference: unbind_op.cc — split a tensor into a LIST of tensors
    along `axis`, removing that axis from each (same op as unstack;
    Tensor.unbind delegates there too)."""
    return unstack(input, axis=axis)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """reference: diag_embed_op.cc — embed the last dim of `input` as a
    diagonal of a new 2D plane appended at (dim1, dim2)."""
    def impl(x, offset, dim1, dim2):
        m = x.shape[-1] + abs(offset)
        out_ndim = x.ndim + 1
        d1 = dim1 % out_ndim
        d2 = dim2 % out_ndim
        # build on trailing axes then move into position
        plane = jnp.zeros(x.shape[:-1] + (m, m), x.dtype)
        idx = jnp.arange(x.shape[-1])
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        plane = plane.at[..., rows, cols].set(x)
        # trailing axes are (ndim-2, ndim-1) = (d1', d2') — move to
        # requested dims, keeping their relative order
        order = sorted((d1, d2))
        src = [out_ndim - 2, out_ndim - 1]
        return jnp.moveaxis(plane, src, order if d1 < d2 else order[::-1])
    return apply(impl, (input,), dict(offset=offset, dim1=dim1, dim2=dim2),
                 name="diag_embed")
