"""paddle_tpu.ops.sequence — sequence ops (padded-tensor semantics).

TPU-native rebuild of the reference's LoD sequence operators
(reference: paddle/fluid/operators/sequence_ops/* — sequence_pool,
sequence_softmax, sequence_expand, sequence_reverse, sequence_pad/unpad;
python surface fluid/layers/sequence_lod.py).

Redesign: LoD (ragged) tensors are hostile to XLA's static shapes, so the
TPU formulation is the padded batch + length vector the reference's
sequence_pad produced anyway: every op takes `[B, T, ...]` data plus
`length: [B]` and masks internally. This matches how the reference models
fed RNNs after padding.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import as_tensor, convert_dtype
from ..dispatch import apply


def _mask(length, t, extra_dims=0):
    m = jnp.arange(t)[None, :] < length[:, None]
    for _ in range(extra_dims):
        m = m[..., None]
    return m


def sequence_pool(x, pool_type, length=None, name=None):
    """reference: sequence_pool_op. x: [B, T, D], length: [B] (None = all
    timesteps valid). pool_type in sum/average/max/min/last/first/sqrt."""
    pool_type = pool_type.lower()

    def impl(x, length, pool_type):
        b, t = x.shape[:2]
        ln = length if length is not None else jnp.full((b,), t, jnp.int32)
        m = _mask(ln, t, x.ndim - 2)
        if pool_type == "sum":
            return jnp.sum(jnp.where(m, x, 0), axis=1)
        if pool_type in ("average", "mean"):
            return jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.maximum(
                ln[:, None].astype(x.dtype), 1)
        if pool_type == "sqrt":
            return jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.sqrt(
                jnp.maximum(ln[:, None].astype(x.dtype), 1))
        if pool_type == "max":
            return jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
        if pool_type == "min":
            return jnp.min(jnp.where(m, x, jnp.inf), axis=1)
        if pool_type == "first":
            return x[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(ln - 1, 0)
            return jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        raise ValueError(pool_type)

    args = (x,) if length is None else (x, as_tensor(length))
    if length is None:
        return apply(lambda x, pool_type: impl(x, None, pool_type), (x,),
                     dict(pool_type=pool_type), name="sequence_pool")
    return apply(lambda x, ln, pool_type: impl(x, ln, pool_type), args,
                 dict(pool_type=pool_type), name="sequence_pool")


def sequence_softmax(x, length=None, name=None):
    """reference: sequence_softmax_op — softmax over valid timesteps."""
    def impl(x, *maybe_len):
        b, t = x.shape[:2]
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        m = _mask(ln, t, x.ndim - 2)
        z = jnp.where(m, x, -jnp.inf)
        out = jax.nn.softmax(z, axis=1)
        return jnp.where(m, out, 0.0)
    args = (x,) if length is None else (x, as_tensor(length))
    return apply(impl, args, name="sequence_softmax")


def sequence_reverse(x, length=None, name=None):
    """reference: sequence_reverse_op — reverse valid prefix per row."""
    def impl(x, *maybe_len):
        b, t = x.shape[:2]
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        idx = jnp.arange(t)[None, :]
        rev = jnp.where(idx < ln[:, None], ln[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            x, rev.reshape(b, t, *([1] * (x.ndim - 2))).astype(jnp.int32),
            axis=1)
    args = (x,) if length is None else (x, as_tensor(length))
    return apply(impl, args, name="sequence_reverse")


def sequence_expand(x, repeat_times, name=None):
    """reference: sequence_expand_op simplified: repeat each row k times
    (uniform k keeps static shapes on TPU)."""
    def impl(x, k):
        return jnp.repeat(x, k, axis=0)
    return apply(impl, (x,), dict(k=repeat_times), name="sequence_expand")


def sequence_pad(sequences, maxlen=None, pad_value=0.0, name=None):
    """Host-side helper (ragged python list -> padded [B, T, ...] + length),
    the analogue of the reference's sequence_pad preprocessing."""
    arrs = [np.asarray(s) for s in sequences]
    t = maxlen or max(len(a) for a in arrs)
    b = len(arrs)
    trailing = arrs[0].shape[1:]
    out = np.full((b, t) + trailing, pad_value, dtype=arrs[0].dtype)
    lens = np.zeros((b,), np.int32)
    for i, a in enumerate(arrs):
        n = min(len(a), t)
        out[i, :n] = a[:n]
        lens[i] = n
    from ..tensor import Tensor
    return Tensor(out), Tensor(lens)


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] + lengths -> list of numpy arrays (host-side,
    dynamic shapes)."""
    x = as_tensor(x)
    ln = np.asarray(jax.device_get(as_tensor(length).data))
    arr = np.asarray(jax.device_get(x.data))
    return [arr[i, :ln[i]] for i in range(arr.shape[0])]


def sequence_concat(xs, name=None):
    from .manip import concat
    return concat(xs, axis=1)


def sequence_first_step(x, length=None, name=None):
    """reference: sequence_lod.py:sequence_first_step (pool 'first')."""
    return sequence_pool(x, "first", length=length)


def sequence_last_step(x, length=None, name=None):
    """reference: sequence_lod.py:sequence_last_step (pool 'last')."""
    return sequence_pool(x, "last", length=length)


def sequence_conv(x, weight, bias=None, filter_size=3, padding_start=None,
                  length=None, name=None):
    """reference: sequence_conv_op (sequence_lod.py:44). Context-window
    convolution over time: each step t sees steps
    [t + padding_start, t + padding_start + filter_size) with zero padding
    outside the valid prefix; the stacked context is projected by `weight`
    ([filter_size * D, num_filters]).

    TPU-first: the context stack is built with static rolls (filter_size is
    a compile-time constant) and the projection is ONE MXU matmul; positions
    beyond `length` are masked to zero, matching LoD boundaries."""
    if padding_start is None:
        # reference default (sequence_lod.py:155): -int(filter_size // 2)
        padding_start = -int(filter_size // 2)
    has_bias = bias is not None
    has_len = length is not None

    def impl(x, w, *rest, filter_size, padding_start, has_bias, has_len):
        bvals = rest[0] if has_bias else None
        ln = rest[1 if has_bias else 0] if has_len else None
        b, t, d = x.shape
        if ln is None:
            ln = jnp.full((b,), t, jnp.int32)
        m = _mask(ln, t, 1)
        xz = jnp.where(m, x, 0.0)
        cols = []
        pos = jnp.arange(t)
        for j in range(filter_size):
            off = padding_start + j
            shifted = jnp.roll(xz, -off, axis=1)
            src = pos + off
            ok = (src >= 0) & (src < ln[:, None])
            cols.append(jnp.where(ok[..., None], shifted, 0.0))
        ctx = jnp.concatenate(cols, axis=-1)  # [B, T, fs*D]
        out = jnp.einsum("btk,kf->btf", ctx, w)
        if bvals is not None:
            out = out + bvals
        return jnp.where(m, out, 0.0)

    args = [x, weight]
    if bias is not None:
        args.append(bias)
    if length is not None:
        args.append(as_tensor(length))
    return apply(impl, tuple(args),
                 dict(filter_size=filter_size, padding_start=padding_start,
                      has_bias=has_bias, has_len=has_len),
                 name="sequence_conv")


def sequence_slice(x, offset, length_per_seq, name=None):
    """reference: sequence_slice_op — per-row slice [offset, offset+len)
    re-packed at the start of each row (padded layout). Output keeps the
    static [B, T, ...] shape; valid width per row is `length_per_seq`."""
    def impl(x, off, sl):
        b, t = x.shape[:2]
        idx = jnp.arange(t)[None, :] + off[:, None]
        idx = jnp.clip(idx, 0, t - 1)
        gathered = jnp.take_along_axis(
            x, idx.reshape(b, t, *([1] * (x.ndim - 2))).astype(jnp.int32),
            axis=1)
        m = _mask(sl.astype(jnp.int32), t, x.ndim - 2)
        return jnp.where(m, gathered, 0)

    return apply(impl, (x, as_tensor(offset), as_tensor(length_per_seq)),
                 name="sequence_slice")


def sequence_expand_as(x, y_length, maxlen=None, name=None):
    """reference: sequence_expand_as_op — row i of x is repeated to the
    width of sequence i: output [B, T, ...] where out[i, t] = x[i] for
    t < y_length[i], else 0. (Padded-batch formulation of the LoD
    broadcast; `maxlen` = static T, defaults to max(y_length) which then
    must be concrete.)"""
    ln = as_tensor(y_length)
    if maxlen is None:
        if isinstance(ln.data, jax.core.Tracer):
            raise ValueError(
                "sequence_expand_as: pass maxlen= (the static T) under "
                "jit/static mode — y_length is traced so its max cannot "
                "size the output")
        # eager: documented host sync to read the dynamic width
        maxlen = int(np.asarray(jax.device_get(ln.data)).max())

    def impl(x, ln, t):
        out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
        m = _mask(ln, t, x.ndim - 1)
        return jnp.where(m, out, 0)

    return apply(impl, (x, ln), dict(t=int(maxlen)),
                 name="sequence_expand_as")


def sequence_reshape(x, new_dim, name=None):
    """reference: sequence_reshape_op — refold the feature dim: [B, T, D]
    -> [B, T*D/new_dim, new_dim]."""
    def impl(x, new_dim):
        b = x.shape[0]
        return x.reshape(b, -1, new_dim)
    return apply(impl, (x,), dict(new_dim=new_dim), name="sequence_reshape")


def sequence_scatter(x, index, updates, name=None):
    """reference: sequence_scatter_op — per-row scatter-add: for row b,
    x[b, index[b, j]] += updates[b, j]."""
    def impl(x, idx, upd):
        def row(xr, ir, ur):
            return xr.at[ir].add(ur)
        return jax.vmap(row)(x, idx.astype(jnp.int32), upd)
    return apply(impl, (x, as_tensor(index), updates),
                 name="sequence_scatter")


def sequence_enumerate(x, win_size, pad_value=0, length=None, name=None):
    """reference: sequence_enumerate_op — sliding windows of ids:
    [B, T] -> [B, T, win_size]; positions past the valid prefix (or past
    the end of a window) are pad_value."""
    def impl(x, *maybe_len, win_size, pad_value):
        b, t = x.shape[:2]
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        pos = jnp.arange(t)[None, :, None] + jnp.arange(win_size)[None, None]
        ok = pos < ln[:, None, None]
        idx = jnp.clip(pos, 0, t - 1)
        # gather x[b, idx[b, t, w]] along the time axis
        win = jnp.take_along_axis(
            jnp.broadcast_to(x[:, :, None], (b, t, win_size)), idx, axis=1)
        return jnp.where(ok, win, pad_value)

    args = (as_tensor(x),) if length is None else (as_tensor(x),
                                                   as_tensor(length))
    return apply(impl, args, dict(win_size=win_size, pad_value=pad_value),
                 nondiff=True, name="sequence_enumerate")
