"""paddle_tpu.ops.sequence — sequence ops (padded-tensor semantics).

TPU-native rebuild of the reference's LoD sequence operators
(reference: paddle/fluid/operators/sequence_ops/* — sequence_pool,
sequence_softmax, sequence_expand, sequence_reverse, sequence_pad/unpad;
python surface fluid/layers/sequence_lod.py).

Redesign: LoD (ragged) tensors are hostile to XLA's static shapes, so the
TPU formulation is the padded batch + length vector the reference's
sequence_pad produced anyway: every op takes `[B, T, ...]` data plus
`length: [B]` and masks internally. This matches how the reference models
fed RNNs after padding.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import as_tensor, convert_dtype
from ..dispatch import apply


def _mask(length, t, extra_dims=0):
    m = jnp.arange(t)[None, :] < length[:, None]
    for _ in range(extra_dims):
        m = m[..., None]
    return m


def sequence_pool(x, pool_type, length=None, name=None):
    """reference: sequence_pool_op. x: [B, T, D], length: [B] (None = all
    timesteps valid). pool_type in sum/average/max/min/last/first/sqrt."""
    pool_type = pool_type.lower()

    def impl(x, length, pool_type):
        b, t = x.shape[:2]
        ln = length if length is not None else jnp.full((b,), t, jnp.int32)
        m = _mask(ln, t, x.ndim - 2)
        if pool_type == "sum":
            return jnp.sum(jnp.where(m, x, 0), axis=1)
        if pool_type in ("average", "mean"):
            return jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.maximum(
                ln[:, None].astype(x.dtype), 1)
        if pool_type == "sqrt":
            return jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.sqrt(
                jnp.maximum(ln[:, None].astype(x.dtype), 1))
        if pool_type == "max":
            return jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
        if pool_type == "min":
            return jnp.min(jnp.where(m, x, jnp.inf), axis=1)
        if pool_type == "first":
            return x[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(ln - 1, 0)
            return jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        raise ValueError(pool_type)

    args = (x,) if length is None else (x, as_tensor(length))
    if length is None:
        return apply(lambda x, pool_type: impl(x, None, pool_type), (x,),
                     dict(pool_type=pool_type), name="sequence_pool")
    return apply(lambda x, ln, pool_type: impl(x, ln, pool_type), args,
                 dict(pool_type=pool_type), name="sequence_pool")


def sequence_softmax(x, length=None, name=None):
    """reference: sequence_softmax_op — softmax over valid timesteps."""
    def impl(x, *maybe_len):
        b, t = x.shape[:2]
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        m = _mask(ln, t, x.ndim - 2)
        z = jnp.where(m, x, -jnp.inf)
        out = jax.nn.softmax(z, axis=1)
        return jnp.where(m, out, 0.0)
    args = (x,) if length is None else (x, as_tensor(length))
    return apply(impl, args, name="sequence_softmax")


def sequence_reverse(x, length=None, name=None):
    """reference: sequence_reverse_op — reverse valid prefix per row."""
    def impl(x, *maybe_len):
        b, t = x.shape[:2]
        ln = maybe_len[0] if maybe_len else jnp.full((b,), t, jnp.int32)
        idx = jnp.arange(t)[None, :]
        rev = jnp.where(idx < ln[:, None], ln[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            x, rev.reshape(b, t, *([1] * (x.ndim - 2))).astype(jnp.int32),
            axis=1)
    args = (x,) if length is None else (x, as_tensor(length))
    return apply(impl, args, name="sequence_reverse")


def sequence_expand(x, repeat_times, name=None):
    """reference: sequence_expand_op simplified: repeat each row k times
    (uniform k keeps static shapes on TPU)."""
    def impl(x, k):
        return jnp.repeat(x, k, axis=0)
    return apply(impl, (x,), dict(k=repeat_times), name="sequence_expand")


def sequence_pad(sequences, maxlen=None, pad_value=0.0, name=None):
    """Host-side helper (ragged python list -> padded [B, T, ...] + length),
    the analogue of the reference's sequence_pad preprocessing."""
    arrs = [np.asarray(s) for s in sequences]
    t = maxlen or max(len(a) for a in arrs)
    b = len(arrs)
    trailing = arrs[0].shape[1:]
    out = np.full((b, t) + trailing, pad_value, dtype=arrs[0].dtype)
    lens = np.zeros((b,), np.int32)
    for i, a in enumerate(arrs):
        n = min(len(a), t)
        out[i, :n] = a[:n]
        lens[i] = n
    from ..tensor import Tensor
    return Tensor(out), Tensor(lens)


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] + lengths -> list of numpy arrays (host-side,
    dynamic shapes)."""
    x = as_tensor(x)
    ln = np.asarray(jax.device_get(as_tensor(length).data))
    arr = np.asarray(jax.device_get(x.data))
    return [arr[i, :ln[i]] for i in range(arr.shape[0])]


def sequence_concat(xs, name=None):
    from .manip import concat
    return concat(xs, axis=1)
